"""Roofline attribution layer tests (ISSUE 13, docs/OBSERVABILITY.md
"Roofline attribution") — CPU backend.

Covers the tentpole surface: the analytic per-stage ledger summing
EXACTLY to ``models.alexnet.flops_per_image`` (one generator feeds
both), staged-vs-fused byte-model monotonicity with the delta equal to
the intermediates' write+read round-trips, compute/memory-bound
classification against the spec table's ridge point, the CPU-mesh
integration joining a REAL ``attribute_stages`` breakdown into a ranked
report, the committed-BENCH acceptance (roofline-over-BENCH_r05
reproduces the bf16 MFU 0.5713 from the row's own fields), the
echo-aware CLI, the one-source-of-truth spec table bench delegates to,
the serve telemetry records (``serve_gauges``/``mem_snapshot``), the
Perfetto counter tracks, and the Prometheus exposition.
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import (  # noqa: E402
    BLOCKS12,
    flops_per_image,
    matmul_flops_per_image,
    stage_flops,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability import (  # noqa: E402
    specs,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability.roofline import (  # noqa: E402
    BLOCKS,
    attribute_roofline,
    fused_blocks,
    model_stage_split,
    pass_ledger,
    roofline_from_bench_row,
    row_views,
    stage_ledger,
)

SMALL = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)
STAGES = ("conv1", "pool1", "conv2", "pool2", "lrn2")


# ---------------------------------------------------------------- ledger ---


def test_stage_flops_ledger_sums_exactly_to_whole_pass_counters():
    """The acceptance contract: the per-stage FLOP ledger and the
    whole-pass counters come from ONE generator, so they agree exactly —
    at the default geometry and a replaced one."""
    for cfg in (BLOCKS12, SMALL):
        rows = list(stage_flops(cfg))
        assert [n for n, _f, _mm in rows] == list(STAGES)
        assert sum(f for _n, f, _mm in rows) == flops_per_image(cfg)
        assert sum(mm for _n, _f, mm in rows) == matmul_flops_per_image(cfg)
        # and the byte ledger carries the same flops, batch-scaled
        for batch in (1, 7):
            entries = pass_ledger(cfg, dtype="fp32", batch=batch)
            assert sum(e.flops for e in entries) == flops_per_image(cfg) * batch
            assert (
                sum(e.matmul_flops for e in entries)
                == matmul_flops_per_image(cfg) * batch
            )


def test_ledger_activation_bytes_chain_and_dtype_policy():
    """Stage k's output activation bytes equal stage k+1's input bytes
    (the staged chain round-trips through HBM between taps), and the
    dtype policy halves activation traffic fp32 -> bf16."""
    fp32 = stage_ledger(BLOCKS12, dtype="fp32")
    bf16 = stage_ledger(BLOCKS12, dtype="bf16")
    for a, b in zip(fp32, fp32[1:]):
        assert a.act_out_bytes == b.act_in_bytes
    for e32, e16 in zip(fp32, bf16):
        assert e32.act_in_bytes == 2 * e16.act_in_bytes
        assert e32.act_out_bytes == 2 * e16.act_out_bytes
    # int8w: int8 weights + fp32 per-channel scales over bf16 activations
    i8 = stage_ledger(BLOCKS12, dtype="int8w")
    c1 = BLOCKS12.conv1
    assert i8[0].act_in_bytes == bf16[0].act_in_bytes
    assert i8[0].param_bytes == (
        c1.filter_size**2 * 3 * c1.out_channels  # int8 weights, 1 byte
        + c1.out_channels * 2  # bf16 bias
        + c1.out_channels * 4  # fp32 scales
    )
    with pytest.raises(ValueError, match="fp32"):
        stage_ledger(BLOCKS12, dtype="fp64")


def test_fused_byte_model_monotone_and_delta_is_intermediate_roundtrips():
    """The satellite contract: fused <= staged for every block and dtype,
    and the delta is EXACTLY the interior boundaries' activations written
    once and read once (2x bytes each)."""
    for dtype in ("fp32", "bf16", "int8w"):
        for batch in (1, 16):
            entries = pass_ledger(BLOCKS12, dtype=dtype, batch=batch)
            by = {e.name: e for e in entries}
            blocks = fused_blocks(entries, 197.0, 819.0)
            assert [b.name for b in blocks] == ["block1", "block2"]
            for b in blocks:
                assert b.fused_bytes <= b.staged_bytes
                # interior boundaries: every stage's output except the last
                interior = sum(
                    by[n].act_out_bytes for n in b.stages[:-1]
                )
                assert b.intermediate_bytes == 2 * interior
                assert b.fused_floor_ms <= b.staged_floor_ms + 1e-12
                assert b.fused_mfu_ceiling is not None
                assert 0 < b.fused_mfu_ceiling <= 1.0


def test_block_structure_matches_the_megakernel_plan():
    assert BLOCKS == (
        ("block1", ("conv1", "pool1")),
        ("block2", ("conv2", "pool2", "lrn2")),
    )


# ----------------------------------------------------------------- specs ---


def test_spec_table_is_the_one_source_bench_delegates_to():
    import bench

    # the historical bench surface delegates: same answers, one table
    assert bench.peak_tflops("TPU v5 lite") == 197.0
    assert bench.peak_tflops("TPU v4") == 275.0
    assert bench.peak_tflops("weird-device") == 197.0  # assumed default
    assert bench._PEAK_TABLE == specs.bf16_peak_table()
    # per-dtype peaks: fp32 is the bf16 peak / 6 (HIGHEST synthesis);
    # int8w runs bf16 MXU passes in this repo (dequant-free forward)
    assert specs.peak_tflops("TPU v5 lite", "fp32") == pytest.approx(197.0 / 6)
    assert specs.peak_tflops("TPU v5 lite", "int8w") == 197.0
    spec, assumed = specs.spec_for("TPU v5 lite")
    assert spec.name == "TPU v5e" and not assumed
    assert spec.hbm_gbps == 819.0
    _spec, assumed = specs.spec_for("cpu")
    assert assumed  # CPU judged against the assumed default, visibly
    # v5p must win over the v5 substring
    assert specs.spec_for("TPU v5p")[0].bf16_tflops == 459.0


def test_peak_env_overrides_still_honored(monkeypatch):
    import bench

    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "100")
    assert bench.peak_tflops("TPU v5 lite") == 100.0
    assert specs.peak_tflops("TPU v5 lite", "fp32") == pytest.approx(100 / 6)
    monkeypatch.setenv("BENCH_PEAK_HBM_GBPS", "500")
    assert specs.hbm_gbps("TPU v5 lite") == 500.0


def test_device_memory_stats_always_reports_a_source():
    snap = specs.device_memory_stats()
    assert snap["source"] in ("device", "rss")
    assert isinstance(snap["bytes_in_use"], int) and snap["bytes_in_use"] > 0


# ------------------------------------------------------------ attribution ---


def test_bound_classification_unit_cases():
    """A stage above the ridge intensity is compute-bound, below it
    memory-bound, and the floors/headroom follow the binding roof."""
    entries = pass_ledger(BLOCKS12, dtype="bf16", batch=128)
    by = {e.name: e for e in entries}
    ridge = 197e12 / 819e9  # ~240 FLOP/byte on the v5e spec
    assert by["conv2"].intensity > ridge  # the MXU stage
    assert by["pool1"].intensity < 1.0  # pure streaming
    rep = attribute_roofline(
        {"conv2": 1.0, "pool1": 1.0},
        dtype="bf16",
        batch=128,
        device_kind="TPU v5 lite",
    )
    verdicts = {s.name: s for s in rep.stages}
    assert verdicts["conv2"].bound == "compute"
    assert verdicts["pool1"].bound == "memory"
    # compute-bound floor = flops/peak; memory-bound floor = bytes/bw
    assert verdicts["conv2"].floor_ms == pytest.approx(
        by["conv2"].flops / 197e12 * 1e3
    )
    assert verdicts["pool1"].floor_ms == pytest.approx(
        by["pool1"].staged_bytes / 819e9 * 1e3
    )
    for s in rep.stages:
        assert s.headroom_ms == pytest.approx(s.ms - s.floor_ms)
    # ranked: biggest reclaimable ms first
    assert [s.headroom_ms for s in rep.stages] == sorted(
        [s.headroom_ms for s in rep.stages], reverse=True
    )


def test_model_stage_split_sums_exactly_to_total():
    entries = pass_ledger(BLOCKS12, dtype="bf16", batch=128)
    split = model_stage_split(5.0, entries, 197.0, 819.0)
    assert set(split) == set(STAGES)
    assert sum(split.values()) == pytest.approx(5.0)
    # the split respects the floors' proportions: conv2 dominates
    assert split["conv2"] == max(split.values())


def test_cpu_mesh_integration_joins_a_real_breakdown():
    """The integration acceptance: a REAL attribute_stages breakdown on
    the CPU mesh joins into a ranked roofline report — 5 stages, MFU and
    verdicts present (judged against the assumed spec, and saying so),
    and the report round-trips through JSON."""
    from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
        deterministic_input,
        init_params_deterministic,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.observability.stages import (
        attribute_stages,
    )

    att = attribute_stages(
        init_params_deterministic(SMALL),
        deterministic_input(4, SMALL),
        SMALL,
        repeats=2,
        warmup=1,
    )
    rep = attribute_roofline(
        dict(att.stages),
        dtype="fp32",
        batch=4,
        device_kind="cpu",
        cfg=SMALL,
        source="breakdown",
        total_ms=att.total_ms,
    )
    assert rep.spec_assumed  # CPU: the v5e default stands in, visibly
    assert rep.source == "breakdown"
    assert {s.name for s in rep.stages} == set(STAGES)
    assert rep.total_ms == pytest.approx(att.total_ms)
    for s in rep.stages:
        assert s.bound in ("compute", "memory")
        assert s.mfu is not None and s.mfu >= 0
        assert s.achieved_gbps >= 0 and s.floor_ms > 0
        if s.ms > 0:  # a clamped-to-zero stage has nothing to reclaim
            # CPU ms vs a TPU roof: headroom is strictly positive
            assert s.headroom_ms > 0
    assert {b.name for b in rep.blocks} == {"block1", "block2"}
    obj = json.loads(json.dumps(rep.to_obj()))
    assert [s["name"] for s in obj["stages"]] == [s.name for s in rep.stages]
    assert obj["fused_pass_mfu_ceiling"] is not None
    assert "roofline" in rep.render() and "fused block1" in rep.render()


# ------------------------------------------------------------ bench rows ---


def test_roofline_over_bench_r05_reproduces_committed_mfu():
    """THE acceptance: the committed BENCH_r05 row's bf16 MFU 0.5713 (and
    fp32 0.1229) recomputed from the row's OWN fields — throughput x
    matmul FLOPs / assumed peak — not read back from the mfu field."""
    obj = json.loads((ROOT / "BENCH_r05.json").read_text())["parsed"]
    reports = {r.dtype: r for r in roofline_from_bench_row(obj)}
    assert set(reports) == {"fp32", "bf16"}
    bf16 = reports["bf16"]
    assert round(bf16.pass_mfu, 4) == 0.5713 == obj["last_good"]["bf16"]["mfu"]
    assert round(reports["fp32"].pass_mfu, 4) == 0.1229 == obj["last_good"]["mfu"]
    for rep in reports.values():
        assert rep.stale  # a last_good carry says so
        assert rep.source == "model"  # pre-PR-9 row: no measured breakdown
        assert rep.device_kind == "TPU v5 lite" and not rep.spec_assumed
        assert {s.name for s in rep.stages} == set(STAGES)
        assert sum(s.ms for s in rep.stages) == pytest.approx(rep.total_ms)
    # per_pass_ms derived for views without it: batch/img_s
    assert bf16.total_ms == pytest.approx(
        obj["last_good"]["bf16"]["per_pass_ms"]
    )


def test_row_views_fresh_vs_stale_and_bf16_inheritance():
    fresh = {
        "value": 100.0, "compute": "fp32", "batch": 8,
        "device_kind": "TPU v4", "assumed_peak_tflops": 275.0,
        "matmul_flops_per_image": 1,
        "bf16": {"value": 300.0, "compute": "bf16"},
    }
    views = row_views(fresh)
    assert [v["dtype"] for v in views] == ["fp32", "bf16"]
    assert all(not v["stale"] for v in views)
    assert views[1]["batch"] == 8  # inherited from the carrier row
    assert views[1]["device_kind"] == "TPU v4"
    # an error round with no last_good has no measurable view
    assert row_views({"value": 0.0, "error": "wedged"}) == []


def test_roofline_cli_over_committed_trail_marks_echoes(tmp_path):
    """The CLI acceptance: over the committed BENCH_r*.json trail the
    roofline CLI ranks the five stages with MFU + bound verdicts, marks
    the r04 echo attributably (gate.py's detection, reused), and never
    ranks it as fresh."""
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.observability",
            "roofline", *sorted(str(p) for p in ROOT.glob("BENCH_r*.json")),
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    assert "stale (echo of BENCH_r03.json)" in out
    assert "echo of BENCH_r03.json — stale carry, not ranked" in out
    for stage in STAGES:
        assert stage in out
    assert "mfu=0.5713" in out  # the committed bf16 headline, recomputed
    assert "STALE (last_good carry)" in out  # carries are labeled
    assert "fused block2 (conv2+pool2+lrn2)" in out
    assert "bound" in out and "compute" in out and "memory" in out
    # --json emits one machine-readable object per rendered view
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.observability",
            "roofline", "--json", str(ROOT / "BENCH_r05.json"),
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")]
    assert {r["dtype"] for r in rows} == {"fp32", "bf16"}
    assert all(r["round"] == "BENCH_r05.json" for r in rows)
    assert all(r["stale"] for r in rows)


def test_roofline_cli_usage_rc2(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.observability",
            "roofline",
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
    )
    assert proc.returncode == 2
    assert "BENCH rows" in proc.stderr
    bad = tmp_path / "nothing.json"
    bad.write_text("not json at all")
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.observability",
            "roofline", str(bad),
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
    )
    assert proc.returncode == 2


# --------------------------------------------------------- live telemetry ---


def test_serve_telemetry_journals_gauges_and_mem_snapshots(tmp_path):
    """The dispatch loop journals serve_gauges (queue saturation trio)
    and mem_snapshot records off the timed path, at the configured
    cadence, with the reading's source named; the mem.* registry gauges
    mirror them."""
    from cuda_mpi_gpu_cluster_programming_tpu.observability.metrics import (
        registry,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal
    from cuda_mpi_gpu_cluster_programming_tpu.serving.server import (
        InferenceServer,
        ServeConfig,
    )

    tiny = dataclasses.replace(BLOCKS12, in_height=35, in_width=35)
    jp = tmp_path / "serve.jsonl"
    srv = InferenceServer(
        ServeConfig(
            config="v1_jit", max_batch=2, model_cfg=tiny,
            journal_path=str(jp), mem_snapshot_s=0.001,
        )
    )
    for i in range(3):
        srv.submit(np.full((1, 35, 35, 3), 1.0 + i, np.float32))
    srv.run_until_drained()
    recs = Journal.load(jp)
    gauges = [r for r in recs if r["kind"] == "serve_gauges"]
    snaps = [r for r in recs if r["kind"] == "mem_snapshot"]
    assert gauges and snaps
    for g in gauges:
        assert {"depth", "pending_images", "oldest_wait_ms", "t_ms"} <= set(g)
    for s in snaps:
        assert s["source"] in ("device", "rss")
        assert isinstance(s["bytes_in_use"], int) and s["bytes_in_use"] > 0
    assert registry().summary().get("mem.bytes_in_use", 0) > 0
    # mem_snapshot_s=0 disables the records entirely
    jp2 = tmp_path / "quiet.jsonl"
    srv2 = InferenceServer(
        ServeConfig(
            config="v1_jit", max_batch=2, model_cfg=tiny,
            journal_path=str(jp2), mem_snapshot_s=0,
        )
    )
    srv2.submit(np.full((1, 35, 35, 3), 1.0, np.float32))
    srv2.run_until_drained()
    kinds = {r["kind"] for r in Journal.load(jp2)}
    assert "mem_snapshot" not in kinds and "serve_gauges" not in kinds


def test_export_renders_counter_tracks_old_journals_unchanged(tmp_path):
    """Gauge-bearing records export as Perfetto counter ("C") events —
    one series per field — while a journal without them yields no counter
    events at all (the old-journal contract)."""
    from cuda_mpi_gpu_cluster_programming_tpu.observability.export import (
        to_trace_events,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal

    jp = tmp_path / "j.jsonl"
    j = Journal(jp)
    j.append("serve_gauges", key="g:1", t_ms=1.0, depth=3,
             pending_images=5, oldest_wait_ms=12.5)
    j.append("mem_snapshot", key="m:1", t_ms=1.0, source="rss",
             bytes_in_use=1024, peak_bytes_in_use=None)
    j.append("serve_batch", key="b:1", bucket=2, batch_ms=3.0)
    trace = to_trace_events(Journal.load(jp))
    cs = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    names = {e["name"] for e in cs}
    assert names == {
        "serve_gauges.depth", "serve_gauges.pending_images",
        "serve_gauges.oldest_wait_ms", "mem_snapshot.bytes_in_use",
    }  # the None-valued peak field skips its series
    depth = next(e for e in cs if e["name"] == "serve_gauges.depth")
    assert depth["args"] == {"depth": 3}
    # same pid lane as the serve records; pid named in metadata
    batch = next(
        e for e in trace["traceEvents"] if e["name"] == "serve_batch"
    )
    assert depth["pid"] == batch["pid"]
    # old journal: zero counter events — and (ISSUE 15) zero compile or
    # incident slices, since those render only from compile_event records
    # and reconstructed incidents, neither of which old journals contain.
    jp2 = tmp_path / "old.jsonl"
    Journal(jp2).append("serve_batch", key="b:1", bucket=2, batch_ms=3.0)
    trace2 = to_trace_events(Journal.load(jp2))
    assert not [e for e in trace2["traceEvents"] if e["ph"] == "C"]
    names2 = {e["name"] for e in trace2["traceEvents"]}
    assert not [n for n in names2 if n.startswith(("compile_event",
                                                   "incident.", "phase."))]


def test_export_renders_compile_events_as_slices(tmp_path):
    """ISSUE 15: compile_event records render as duration slices on the
    supervisor lane's compile sub-lane, sized by their measured ms."""
    from cuda_mpi_gpu_cluster_programming_tpu.observability.export import (
        to_trace_events,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal

    jp = tmp_path / "c.jsonl"
    j = Journal(jp)
    j.append("compile_event", key="compile:sup:halo8:b1", site="sup",
             entry="halo8", shape=[1, 67, 67, 3], batch=1, dtype="fp32",
             n_shards=2, ms=120.0, cache_hit=False, xla_flops=1.0e9,
             xla_bytes=2.0e6, t_ms=500.0)
    j.append("compile_event", key="compile:sup:halo8:b1", site="sup",
             entry="halo8", shape=[1, 67, 67, 3], batch=1, dtype="fp32",
             n_shards=2, ms=0.2, cache_hit=True, xla_flops=None,
             xla_bytes=None, t_ms=900.0)
    trace = to_trace_events(Journal.load(jp))
    slices = [e for e in trace["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "compile_event"]
    assert len(slices) == 2
    big = max(slices, key=lambda e: e["dur"])
    assert big["dur"] >= 120.0 * 1e3 * 0.99  # us, sized by measured ms
    assert big["args"]["cache_hit"] is False


def test_prometheus_exposition_format():
    from cuda_mpi_gpu_cluster_programming_tpu.observability.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    reg.counter("serve.ok").inc(4)
    reg.gauge("serve.queue_depth").set(2)
    h = reg.histogram("serve.request_ms")
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    text = reg.prometheus()
    lines = text.splitlines()
    assert "# TYPE serve_ok counter" in lines and "serve_ok 4" in lines
    assert "# TYPE serve_queue_depth gauge" in lines
    assert "serve_queue_depth 2.0" in lines
    assert "# TYPE serve_request_ms summary" in lines
    assert 'serve_request_ms{quantile="0.5"} 2.0' in lines
    assert 'serve_request_ms{quantile="0.99"} 3.0' in lines
    assert "serve_request_ms_sum 6.0" in lines
    assert "serve_request_ms_count 3" in lines
    # dotted names sanitize; an unset gauge renders NaN, not a crash
    reg.gauge("odd.na").to_obj()
    assert "odd_na NaN" in reg.prometheus()

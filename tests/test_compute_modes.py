"""bf16 compute mode: every tier/strategy, close to fp32, fp32 output dtype.

The bf16 mode has no reference analogue (all CUDA stages are fp32) — it is
the TPU-native perf path: bf16 operands, fp32 MXU accumulation, fp32 output.
"""

import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.configs import REGISTRY, build_forward
from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
    deterministic_input,
    init_params_deterministic,
)

GOLDEN_FIRST4 = np.array([29.2932, 25.9153, 23.3255, 23.3255], np.float32)


@pytest.mark.parametrize(
    "key,shards",
    [
        ("v1_jit", 1),
        ("v3_pallas", 1),
        ("v2.2_sharded", 4),
        ("v5_collective", 8),
        ("v4_hybrid", 2),
    ],
)
def test_bf16_close_to_fp32(key, shards):
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    cfg = REGISTRY[key]
    exact = np.asarray(build_forward(cfg, n_shards=shards)(params, x))
    fast = np.asarray(build_forward(cfg, n_shards=shards, compute="bf16")(params, x))
    assert fast.dtype == np.float32
    assert fast.shape == exact.shape
    # bf16 has ~8 mantissa bits; the deterministic workload is smooth, so
    # 2% relative agreement is ample to catch wiring bugs without flaking.
    np.testing.assert_allclose(fast, exact, rtol=2e-2, atol=1e-2)


def test_bf16_golden_neighborhood():
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    out = np.asarray(build_forward(REGISTRY["v1_jit"], compute="bf16")(params, x))
    np.testing.assert_allclose(out[0].reshape(-1)[:4], GOLDEN_FIRST4, rtol=2e-2)


def test_unknown_compute_rejected():
    with pytest.raises(ValueError, match="compute mode"):
        build_forward(REGISTRY["v1_jit"], compute="fp16")


def test_bf16_full_model():
    from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet_full import (
        init_full_deterministic,
    )

    params = init_full_deterministic()
    x = deterministic_input(batch=2)
    cfg = REGISTRY["v6_full_jit"]
    exact = np.asarray(build_forward(cfg)(params, x))
    fast = np.asarray(build_forward(cfg, compute="bf16")(params, x))
    assert fast.shape == exact.shape
    # Deterministic-init logits are uniform across classes; only closeness
    # of the (large-magnitude) values is meaningful here.
    np.testing.assert_allclose(fast, exact, rtol=5e-2, atol=5e-2)

"""Analysis ETL tests: ingest/dedup, views, speedup math, plot, export.

Reference analogue: log_analysis.py's DuckDB pipeline (SURVEY §1 L6, §2.4 H6).
"""

import shutil
from pathlib import Path

import pytest

from cuda_mpi_gpu_cluster_programming_tpu import analysis, harness


def _fake_session(tmp_path: Path) -> harness.Session:
    """Build a session dir with CSV rows mimicking a V1/V2.2 sweep."""
    session = harness.Session(log_root=tmp_path / "logs", session_id="s1", machine_id="m1")
    cases = [
        ("V1 Serial", "v1_jit", 1, 100.0),
        ("V1 Serial", "v1_jit", 1, 120.0),
        ("V2.2 ScatterHalo", "v2.2_sharded", 1, 90.0),
        ("V2.2 ScatterHalo", "v2.2_sharded", 2, 50.0),
        ("V2.2 ScatterHalo", "v2.2_sharded", 4, 25.0),
    ]
    for variant, key, np_, ms in cases:
        r = harness.CaseResult(variant, key, np_, 1)
        r.run_status = harness.OK
        r.time_ms = ms
        r.shape = "13x13x256"
        r.first5 = "29.2932 25.9153"
        session.log_row(r)
    (session.dir / "run_v1_jit_np1_b1.log").write_text(
        "Final Output Shape: 13x13x256\n"
        "AlexNet TPU Forward Pass completed in 100.000 ms (amortized)\n"
    )
    return session


def test_ingest_views_and_dedup(tmp_path):
    session = _fake_session(tmp_path)
    db = tmp_path / "w.sqlite"
    conn = analysis.connect(db)
    analysis.cmd_ingest(conn, session.log_root, None)
    rows = conn.execute("SELECT COUNT(*) FROM summary_runs").fetchone()[0]
    assert rows == 5
    assert conn.execute("SELECT COUNT(*) FROM run_logs").fetchone()[0] == 1
    # perf_runs filters to OK rows with time
    assert conn.execute("SELECT COUNT(*) FROM perf_runs").fetchone()[0] == 5
    # best_runs picks min over the two V1 samples
    best = dict(
        (tuple(r[:2]), r[3])
        for r in conn.execute("SELECT variant, np, batch, best_ms FROM best_runs")
    )
    assert best[("V1 Serial", 1)] == 100.0
    # run_stats: mean/stddev/ci over V1 Serial (platform column appended
    # round 3 — one machine's sessions span CPU fallback and tunneled TPU,
    # so stats group per platform)
    v, np_, b, n, mean, sd, ci, corpus, platform = conn.execute(
        "SELECT * FROM run_stats WHERE variant='V1 Serial'"
    ).fetchone()
    assert corpus == "local"
    assert n == 2 and abs(mean - 110.0) < 1e-9
    assert abs(sd - 14.142135623730951) < 1e-6
    # SHA1-incremental re-ingest: unchanged files are skipped, rows not duplicated
    analysis.cmd_ingest(conn, session.log_root, None)
    assert conn.execute("SELECT COUNT(*) FROM summary_runs").fetchone()[0] == 5
    conn.close()


def test_speedup_math(tmp_path):
    session = _fake_session(tmp_path)
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, session.log_root, None)
    rows = analysis.cmd_speedup(conn, "V1 Serial")
    by = {(r[0], r[1]): r for r in rows}
    # S(N) = T1/TN against the best V1 np=1 (100 ms)
    assert abs(by[("V2.2 ScatterHalo", 4)][4] - 100.0 / 25.0) < 1e-9
    # E(N) = S/N
    assert abs(by[("V2.2 ScatterHalo", 4)][5] - 1.0) < 1e-9
    assert abs(by[("V1 Serial", 1)][4] - 1.0) < 1e-9
    conn.close()


def test_canonical_variant_mapping():
    assert analysis.canonical_variant("v2.2") == "V2.2 ScatterHalo"
    assert analysis.canonical_variant("V1 Serial") == "V1 Serial"
    assert analysis.canonical_variant("V6 TPU Mesh") == "V6 TPU Mesh"  # passthrough


def test_plot_and_export(tmp_path):
    session = _fake_session(tmp_path)
    db = tmp_path / "w.sqlite"
    conn = analysis.connect(db)
    analysis.cmd_ingest(conn, session.log_root, Path("."))
    analysis.cmd_plot(conn, tmp_path / "plots", "V1 Serial")
    assert (tmp_path / "plots" / "speedup.png").exists()
    assert (tmp_path / "plots" / "efficiency.png").exists()
    analysis.cmd_export(conn, "best_runs", tmp_path / "best.csv", "csv")
    text = (tmp_path / "best.csv").read_text()
    assert "V2.2 ScatterHalo" in text
    analysis.cmd_export(conn, "best_runs", tmp_path / "best.parquet", "parquet")
    assert (tmp_path / "best.parquet").stat().st_size > 0
    # source stats were collected from the repo root
    assert conn.execute("SELECT COUNT(*) FROM source_stats").fetchone()[0] > 10
    conn.close()


REFERENCE = Path("/root/reference")


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference corpus not mounted")
def test_reference_corpus_ingest_end_to_end(tmp_path):
    """Ingest the reference's ACTUAL checked-in CSVs (both schema
    generations) and reproduce its best_runs.md numbers (best_runs.md:1-24).

    gen-1: all_runs.csv (ts/version/np/total_time_s export schema).
    gen-2: a session summary CSV (ProjectVariant/OverallStatusSymbol schema,
    status symbols, run_*.log files alongside).
    """
    logs = tmp_path / "logs"
    logs.mkdir()
    shutil.copy(REFERENCE / "all_runs.csv", logs / "all_runs.csv")
    shutil.copy(
        REFERENCE / "final_project" / "logs" / "summary_20250509_115115_nixos.csv",
        logs / "summary_20250509_115115_nixos.csv",
    )
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, logs, None)

    # gen-1 rows (144) + gen-2 session rows (11) all landed
    n = conn.execute("SELECT COUNT(*) FROM summary_runs").fetchone()[0]
    assert n == 155, n
    # raw variant strings were canonicalised (analysis.md:60-80 mapping)
    variants = {r[0] for r in conn.execute("SELECT DISTINCT variant FROM summary_runs")}
    assert {"V1 Serial", "V2.1 BroadcastAll", "V2.2 ScatterHalo", "V3 CUDA", "V4 MPI+CUDA"} <= variants
    assert not any(v.startswith("V2 2.") for v in variants), variants
    # gen-1 rows carry Status=OK so they reach perf_runs (no silent drop)
    n_perf = conn.execute("SELECT COUNT(*) FROM perf_runs").fetchone()[0]
    assert n_perf >= 144, n_perf

    # the corpus reproduces the reference's own best_runs.md numbers
    rows = analysis.cmd_speedup(conn, "V1 Serial")
    best = {(r[0], r[1]): r[3] for r in rows}
    assert abs(best[("V1 Serial", 1)] - 601.0) < 0.5  # best_runs.md:6-7
    assert abs(best[("V4 MPI+CUDA", 1)] - 182.901) < 0.5  # best_runs.md:16
    assert abs(best[("V2.2 ScatterHalo", 4)] - 186.236) < 0.5  # best_runs.md:21
    # S(4) for V2.2 = 3.23, E = 0.81 (best_runs.md / SURVEY §6)
    by = {(r[0], r[1]): r for r in rows}
    assert abs(by[("V2.2 ScatterHalo", 4)][4] - 3.23) < 0.01
    assert abs(by[("V2.2 ScatterHalo", 4)][5] - 0.81) < 0.005
    conn.close()


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference corpus not mounted")
def test_per_corpus_speedup_baseline(tmp_path):
    """Reference rows are judged against the reference's OWN V1 baseline,
    local (TPU) rows against theirs — no cross-corpus T1 conflation.

    Regression for the round-2 verdict finding: the reference's V1 np=1 row
    must show S(N)=1.00 even when this repo's (much faster) batch-1 rows
    share the warehouse. Reference semantics: log_analysis.py:213-222.
    """
    logs = tmp_path / "logs"
    # Ingest the reference corpus from its real path so src_csv marks it.
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, REFERENCE / "final_project" / "logs", None)
    # A local session with a dramatically faster V1 np=1 batch-1 row.
    session = harness.Session(log_root=logs, session_id="tpu1", machine_id="tpu-host")
    for np_, ms in [(1, 1.7), (2, 1.0)]:
        r = harness.CaseResult("V1 Serial", "v1_jit", np_, 1)
        r.run_status = harness.OK
        r.time_ms = ms
        r.shape = "13x13x256"
        r.first5 = "29.2932 25.9153"
        session.log_row(r)
    analysis.cmd_ingest(conn, logs, None)

    rows = analysis.cmd_speedup(conn, "V1 Serial")
    by = {(r[6], r[0], r[1]): r for r in rows}
    # Reference V1 np=1 vs its own corpus: exactly 1.00, not 0.00x.
    assert abs(by[("reference", "V1 Serial", 1)][4] - 1.0) < 1e-9
    # Local V1 np=1 likewise 1.00 against the local corpus.
    assert abs(by[("local", "V1 Serial", 1)][4] - 1.0) < 1e-9
    conn.close()


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference corpus not mounted")
def test_reference_plus_tpu_combined_plot(tmp_path):
    """Historical reference data and new TPU-family data land in one
    warehouse and plot on the same axes (SURVEY §7.3 harness-parity goal)."""
    logs = tmp_path / "logs"
    logs.mkdir()
    shutil.copy(REFERENCE / "all_runs.csv", logs / "all_runs.csv")
    session = harness.Session(log_root=logs, session_id="tpu1", machine_id="tpu-host")
    # batch=1 so the rows share a per-image baseline with the (batch-less,
    # implicitly batch-1) reference corpus — see SPEEDUP_SQL's COALESCE.
    for np_, ms in [(1, 12.0), (2, 6.5), (4, 3.4)]:
        r = harness.CaseResult("V6 TPU ScatterHalo", "v2.2_sharded", np_, 1)
        r.run_status = harness.OK
        r.time_ms = ms
        r.shape = "13x13x256"
        r.first5 = "29.2932 25.9153"
        session.log_row(r)
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, logs, None)
    variants = {r[0] for r in conn.execute("SELECT DISTINCT variant FROM perf_runs")}
    assert "V6 TPU ScatterHalo" in variants and "V4 MPI+CUDA" in variants
    analysis.cmd_plot(conn, tmp_path / "plots", "V1 Serial")
    assert (tmp_path / "plots" / "speedup.png").exists()
    assert (tmp_path / "plots" / "efficiency.png").exists()
    conn.close()


def test_report_markdown(tmp_path):
    """`report` emits the best_runs.md / *_report.md analogue (ref H7)."""
    session = _fake_session(tmp_path)
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, session.log_root, None)
    out = tmp_path / "report.md"
    analysis.cmd_report(conn, out, "V1 Serial")
    text = out.read_text()
    assert "# Performance analysis report" in text
    assert "## Best runs" in text and "## Run statistics" in text
    assert "| V2.2 ScatterHalo | 4 |" in text
    # speedup section computed: S(4) = 100/25 = 4.00
    assert "| 4.00 |" in text
    conn.close()


def test_cli_end_to_end(tmp_path, capsys):
    session = _fake_session(tmp_path)
    db = str(tmp_path / "w.sqlite")
    assert analysis.main(["--db", db, "ingest", "--logs", str(session.log_root), "--repo-root", ""]) == 0
    assert analysis.main(["--db", db, "stats"]) == 0
    assert analysis.main(["--db", db, "speedup"]) == 0
    out = capsys.readouterr().out
    assert "V2.2 ScatterHalo" in out and "4.00" in out


def test_platform_split_stats_and_baselines(tmp_path):
    """One machine's sessions span the CPU fallback and the tunneled TPU;
    stats and speedup baselines must group per platform — pooling 11 ms CPU
    passes with 0.3 ms TPU passes fabricates wild stddevs and judges TPU
    rows against a CPU baseline. Platform comes from the run log's
    'Devices: N x <kind> (<platform>)' line, falling back to the session
    env.json JAX_PLATFORMS ('axon' = tunneled TPU)."""
    import json

    for sid, platform, ms in (("scpu", "cpu", 100.0), ("stpu", "tpu", 1.0)):
        session = harness.Session(
            log_root=tmp_path / "logs", session_id=sid, machine_id="m1"
        )
        for t in (ms, ms * 1.2):
            r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
            r.run_status = harness.OK
            r.time_ms = t
            r.shape = "13x13x256"
            r.log_file = "run_v1.log"
            session.log_row(r)
        kind = "TPU v5 lite (tpu)" if platform == "tpu" else "cpu (cpu)"
        (session.dir / "run_v1.log").write_text(f"Devices: 1 x {kind}\n")
        (session.dir / "env.json").write_text(
            json.dumps({"env": {"JAX_PLATFORMS": "axon" if platform == "tpu" else "cpu"}})
        )

    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, tmp_path / "logs", None)
    stats = {
        row[-1]: row
        for row in conn.execute("SELECT * FROM run_stats WHERE variant='V1 Serial'")
    }
    assert set(stats) == {"cpu", "tpu"}  # two groups, not one pooled mess
    assert stats["cpu"][3] == 2 and abs(stats["cpu"][4] - 110.0) < 1e-9
    assert stats["tpu"][3] == 2 and abs(stats["tpu"][4] - 1.1) < 1e-9
    # each platform gets its own T1 baseline: both np=1 rows show S(N)=1.0
    rows = analysis.cmd_speedup(conn, "V1 Serial")
    speedups = {r[7]: r[4] for r in rows if r[0] == "V1 Serial"}
    assert abs(speedups["cpu"] - 1.0) < 1e-9
    assert abs(speedups["tpu"] - 1.0) < 1e-9
    conn.close()


def test_platform_backfill_on_legacy_warehouse(tmp_path):
    """Opening a pre-platform-column warehouse backfills the column from
    the recorded src_csv/log_file paths — the sha1-incremental ingest never
    revisits unchanged CSVs, so without the backfill old CPU and TPU rows
    would pool in one NULL-platform group forever."""
    import json
    import sqlite3

    session = harness.Session(log_root=tmp_path / "logs", session_id="s1", machine_id="m1")
    r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
    r.run_status = harness.OK
    r.time_ms = 1.0
    r.log_file = "run_v1.log"
    session.log_row(r)
    (session.dir / "run_v1.log").write_text("Devices: 1 x TPU v5 lite (tpu)\n")
    (session.dir / "env.json").write_text(json.dumps({"env": {"JAX_PLATFORMS": "axon,cpu"}}))

    # Build a legacy warehouse by hand: no platform column, row pre-ingested.
    db = tmp_path / "w.sqlite"
    legacy = sqlite3.connect(db)
    legacy.execute(
        "CREATE TABLE summary_runs ("
        "session_id TEXT, machine_id TEXT, git_commit TEXT, ts TEXT,"
        "variant TEXT, config_key TEXT, np INTEGER, batch INTEGER,"
        "build_status TEXT, run_status TEXT, parse_status TEXT, status TEXT,"
        "time_ms REAL, compile_ms REAL, shape TEXT, first5 TEXT,"
        "log_file TEXT, src_csv TEXT, corpus TEXT)"
    )
    legacy.execute(
        "INSERT INTO summary_runs VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
        ("s1", "m1", None, None, "V1 Serial", "v1_jit", 1, 1, "OK", "OK", "OK",
         "OK", 1.0, None, "13x13x256", None, "run_v1.log",
         str(session.dir / "summary.csv"), "local"),
    )
    legacy.commit()
    legacy.close()

    conn = analysis.connect(db)  # migration: ALTER + backfill
    got = conn.execute("SELECT platform FROM summary_runs").fetchone()[0]
    assert got == "tpu"
    conn.close()
    # The backfill must COMMIT: read-only subcommands close without
    # committing, which would roll the UPDATEs back (regression test for
    # the round-3 review finding — value was 'tpu' in-connection but NULL
    # after close).
    conn = analysis.connect(db)
    assert conn.execute("SELECT platform FROM summary_runs").fetchone()[0] == "tpu"
    conn.close()


def test_narrative_generates_on_any_warehouse(tmp_path):
    """The H7 narrative artifact: generates on a small local-only warehouse
    (reference corpus absent -> pending wording, no crash), includes the
    stage map and the static comm plan, and excludes clamp-floor rows."""
    session = _fake_session(tmp_path)
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, session.log_root, None)
    out = tmp_path / "ANALYSIS.md"
    analysis.cmd_narrative(conn, out, "V1 Serial")
    text = out.read_text()
    assert "# Analysis narrative" in text
    assert "v2.1_replicated" in text  # the stage map
    assert "Where the bytes go" in text  # static comm plan section
    assert "Regenerate:" in text
    conn.close()


def test_narrative_empty_warehouse(tmp_path):
    """No ingested rows at all: still writes a coherent document."""
    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_narrative(conn, tmp_path / "A.md", "V1 Serial")
    text = (tmp_path / "A.md").read_text()
    assert "# Analysis narrative" in text
    conn.close()

"""scripts/session_spread.py: the work-floor protocol's acceptance check.

Validates the comparison logic off-chip (the real input is two heal-window
TPU sessions): common-cell matching, the sub-3 ms bar, the exit code
contract on_heal.sh logs, and the real-backend session filter that keeps
--fake-devices smoke sessions out of the auto-selection.
"""

import csv
import importlib.util
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "session_spread", ROOT / "scripts" / "session_spread.py"
)
session_spread = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(session_spread)


def write_session(root: Path, name: str, cells, backend: str = "tpu") -> Path:
    """cells: list of (variant, config, np, batch, status, time_ms)."""
    d = root / name
    d.mkdir(parents=True)
    cols = [
        "SessionID", "MachineID", "GitCommit", "Timestamp", "Variant",
        "ConfigKey", "NP", "Batch", "BuildStatus", "BuildMsg", "RunStatus",
        "RunMsg", "ParseStatus", "ParseMsg", "Status", "ExecutionTime_ms",
        "Compile_ms", "OutputShape", "First5Values", "LogFile",
    ]
    with open(d / "summary.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for variant, config, np_, batch, status, ms in cells:
            w.writerow({
                "SessionID": name, "Variant": variant, "ConfigKey": config,
                "NP": np_, "Batch": batch, "Status": status,
                "ExecutionTime_ms": "" if ms is None else f"{ms:.3f}",
            })
    (d / "run_case.log").write_text(
        f"$ cmd\nDevices: 1 x TPU v5 lite ({backend})\nFinal Output Shape: x\n"
    )
    return d


def run_main(args, capsys):
    rc = session_spread.main(args)
    return rc, capsys.readouterr().out


def test_pass_within_bar(tmp_path, capsys):
    a = write_session(tmp_path, "bench_a", [("V1", "v1_jit", "1", "32", "OK", 1.00)])
    b = write_session(tmp_path, "bench_b", [("V1", "v1_jit", "1", "32", "OK", 1.05)])
    rc, out = run_main(["--sessions", str(a), str(b)], capsys)
    assert rc == 0
    assert "PASS" in out and "4.9%" in out


def test_fail_over_bar_only_for_sub3ms_cells(tmp_path, capsys):
    # 40% spread on a 10 ms cell is NOT a failure (the claim is about the
    # sub-3 ms rows); 40% on a 1 ms cell is.
    a = write_session(tmp_path, "bench_a", [
        ("V1", "v1_jit", "1", "128", "OK", 10.0),
        ("V3", "v3_pallas", "1", "1", "OK", 1.0),
    ])
    b = write_session(tmp_path, "bench_b", [
        ("V1", "v1_jit", "1", "128", "OK", 15.0),
        ("V3", "v3_pallas", "1", "1", "OK", 1.5),
    ])
    rc, out = run_main(["--sessions", str(a), str(b)], capsys)
    assert rc == 1
    assert "FAIL: V3 np=1 b=1" in out and "V1" not in out.split("FAIL:")[1]


def test_only_common_ok_cells_compared(tmp_path, capsys):
    a = write_session(tmp_path, "bench_a", [
        ("V1", "v1_jit", "1", "32", "OK", 5.0),
        ("V3", "v3_pallas", "1", "32", "TIMEOUT", None),
    ])
    b = write_session(tmp_path, "bench_b", [
        ("V1", "v1_jit", "1", "32", "OK", 5.0),
        ("V3", "v3_pallas", "1", "32", "OK", 5.0),
    ])
    rc, out = run_main(["--sessions", str(a), str(b)], capsys)
    assert rc == 0
    assert "(1 common cells)" in out


def test_auto_selection_skips_cpu_sessions(tmp_path, capsys):
    """A --fake-devices smoke session (Devices banner '(cpu)') between heal
    windows must not be auto-compared against a TPU session."""
    write_session(tmp_path, "bench_1_tpu", [("V1", "v1_jit", "1", "32", "OK", 1.0)])
    write_session(tmp_path, "bench_2_tpu", [("V1", "v1_jit", "1", "32", "OK", 1.0)])
    cpu = write_session(
        tmp_path, "bench_3_cpu", [("V1", "v1_jit", "1", "32", "OK", 400.0)],
        backend="cpu",
    )
    # Make the cpu session the newest — mtime-ordered selection would pick it.
    import os
    import time
    now = time.time()
    os.utime(cpu, (now + 60, now + 60))
    rc, out = run_main(["--logs", str(tmp_path)], capsys)
    assert rc == 0
    assert "bench_1_tpu" in out and "bench_2_tpu" in out and "cpu" not in out


def test_fewer_than_two_real_sessions_is_a_noop(tmp_path, capsys):
    write_session(tmp_path, "bench_only", [("V1", "v1_jit", "1", "32", "OK", 1.0)])
    rc, out = run_main(["--logs", str(tmp_path)], capsys)
    assert rc == 0
    assert "nothing to compare" in out


def test_out_persists_json_and_defaults_off(tmp_path, capsys):
    """--out writes the machine-readable comparison the narrative quotes;
    the default is OFF so test/ad-hoc invocations cannot clobber the
    canonical perf/session_spread_latest.json (review finding)."""
    import json
    write_session(tmp_path, "bench_1_tpu", [("V1", "v1_jit", "1", "1", "OK", 0.2)])
    write_session(tmp_path, "bench_2_tpu", [("V1", "v1_jit", "1", "1", "OK", 0.5)])
    out = tmp_path / "spread.json"
    rc, _ = run_main(["--logs", str(tmp_path), "--out", str(out)], capsys)
    assert rc == 1  # 0.2 vs 0.5 ms: sub-3ms spread way over the bar
    d = json.loads(out.read_text())
    assert d["sessions"] == ["bench_1_tpu", "bench_2_tpu"]
    assert d["failed_cells"] == ["V1 np=1 b=1"]
    assert d["cells"][0]["batch"] == 1 and d["cells"][0]["sub3ms"] is True
    assert 0.85 < d["worst_sub3ms_spread"] < 0.86
    # default: no file appears anywhere
    before = set(Path.cwd().rglob("session_spread_latest.json"))
    rc, _ = run_main(["--logs", str(tmp_path)], capsys)
    assert set(Path.cwd().rglob("session_spread_latest.json")) == before


# keep the module import honest if pytest reruns within one process
sys.modules.setdefault("session_spread", session_spread)

"""Transformer LM: attention-impl parity, training convergence, guards."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
    TINY_LM,
    TransformerConfig,
    forward_lm,
    init_transformer,
    lm_loss,
    make_lm_train_step,
)


@pytest.fixture(scope="module")
def setup():
    params = init_transformer(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, TINY_LM.vocab)
    return params, tokens


class TestForward:
    def test_shapes(self, setup):
        params, tokens = setup
        logits = forward_lm(params, tokens)
        assert logits.shape == (2, 64, TINY_LM.vocab)

    @pytest.mark.parametrize("impl,shards", [("flash", 1), ("ring", 8), ("ulysses", 4)])
    def test_attention_impl_parity(self, setup, impl, shards):
        params, tokens = setup
        cfg = dataclasses.replace(TINY_LM, attn_impl=impl, sp_shards=shards)
        ref = forward_lm(params, tokens)
        got = forward_lm(params, tokens, cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=3e-4, atol=3e-4)

    def test_causality(self, setup):
        # Future-token perturbation must not change past logits.
        params, tokens = setup
        logits = forward_lm(params, tokens)
        perturbed = tokens.at[:, 40:].set((tokens[:, 40:] + 1) % TINY_LM.vocab)
        logits2 = forward_lm(params, perturbed)
        np.testing.assert_allclose(
            np.asarray(logits[:, :40]), np.asarray(logits2[:, :40]), rtol=1e-5, atol=1e-5
        )

    def test_too_long_rejected(self, setup):
        params, _ = setup
        tokens = jnp.zeros((1, TINY_LM.max_len + 1), jnp.int32)
        with pytest.raises(ValueError, match="exceeds max_len"):
            forward_lm(params, tokens)

    def test_bf16(self, setup):
        params, tokens = setup
        pb = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
        ref = forward_lm(params, tokens)
        got = forward_lm(pb, tokens)
        assert got.dtype == jnp.bfloat16
        # Loose: 2-layer net in bf16.
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref), rtol=0.1, atol=0.3
        )


class TestTraining:
    def test_loss_decreases_on_pattern(self):
        # A repeating byte pattern is learnable in a few dozen steps.
        cfg = dataclasses.replace(TINY_LM, n_layers=1, d_model=64, d_ff=128, n_heads=2)
        params = init_transformer(jax.random.PRNGKey(0), cfg)
        pattern = jnp.tile(jnp.arange(8, dtype=jnp.int32), 9)[None, :64].repeat(4, 0)
        opt_init, step = make_lm_train_step(cfg, lr=3e-3)
        opt_state = opt_init(params)
        first = None
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, pattern)
            first = float(loss) if first is None else first
        assert float(loss) < first * 0.5, (first, float(loss))

    def test_ring_training_step_runs(self):
        cfg = dataclasses.replace(TINY_LM, attn_impl="ring", sp_shards=8, n_layers=1)
        params = init_transformer(jax.random.PRNGKey(0), cfg)
        # 65 tokens: the next-token shift leaves L=64, divisible by 8 shards.
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, cfg.vocab)
        opt_init, step = make_lm_train_step(cfg)
        p1, _, loss = step(params, opt_init(params), tokens)
        assert np.isfinite(float(loss))
        # Gradients must match the single-device impl.
        ref_loss = lm_loss(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)


def test_attn_engine_validation():
    """Both flash compositions are accepted since the joint (out, lse) VJP
    landed (round 4): ulysses+flash (whole-sequence VJP) and ring+flash
    (per-hop VJP) train; only unknown engines are rejected."""
    TransformerConfig(attn_impl="ulysses", attn_engine="flash")  # fine
    TransformerConfig(attn_impl="ring", attn_engine="flash")  # trains too now
    with pytest.raises(ValueError, match="attn_engine"):
        TransformerConfig(attn_engine="warp")


def test_ring_flash_lm_trains():
    """An LM with ring+flash attention takes a training step and matches
    the single-device loss — the capability the old config guard denied."""
    import jax

    from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        make_lm_train_step,
    )

    cfg = TransformerConfig(
        d_model=32, n_heads=2, n_layers=2, d_ff=64, max_len=64,
        attn_impl="ring", attn_engine="flash", sp_shards=4,
    )
    ref_cfg = TransformerConfig(d_model=32, n_heads=2, n_layers=2, d_ff=64, max_len=64)
    key = jax.random.PRNGKey(0)
    params = init_transformer(key, cfg)
    tokens = jax.random.randint(key, (2, 33), 0, cfg.vocab)  # shifted len 32 = 4*8
    opt_init, step = make_lm_train_step(cfg, lr=1e-3)
    p1, _, loss = step(params, opt_init(params), tokens)
    jax.block_until_ready(p1)
    assert np.isfinite(float(loss))
    np.testing.assert_allclose(
        float(loss), float(lm_loss(params, tokens, ref_cfg)), rtol=1e-3
    )


def test_remat_same_loss_and_grads():
    """cfg.remat wraps each block in jax.checkpoint: the jaxpr gains remat
    regions, while loss and gradients are unchanged."""
    import jax

    from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
        init_transformer,
        lm_loss,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.breakdown import (
        count_primitive,
    )

    base = TransformerConfig(d_model=32, n_heads=2, n_layers=3, d_ff=64, max_len=32)
    rcfg = TransformerConfig(
        d_model=32, n_heads=2, n_layers=3, d_ff=64, max_len=32, remat=True
    )
    key = jax.random.PRNGKey(0)
    params = init_transformer(key, base)
    tokens = jax.random.randint(key, (2, 17), 0, base.vocab)

    g_base = jax.grad(lambda p: lm_loss(p, tokens, base))(params)
    g_remat = jax.grad(lambda p: lm_loss(p, tokens, rcfg))(params)
    np.testing.assert_allclose(
        float(lm_loss(params, tokens, rcfg)), float(lm_loss(params, tokens, base)),
        rtol=1e-6,
    )
    for a, b in zip(jax.tree.leaves(g_remat), jax.tree.leaves(g_base)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    # remat actually engaged: checkpoint regions appear in the grad jaxpr
    jaxpr = jax.make_jaxpr(lambda p: jax.grad(lambda q: lm_loss(q, tokens, rcfg))(p))(params)
    assert count_primitive(jaxpr, "remat") + count_primitive(jaxpr, "remat2") > 0


def test_grad_accumulation_matches_full_batch():
    """accum_steps=4 (scanned microbatches, one optimizer update) equals
    the full-batch step exactly up to fp reassociation; indivisible batch
    rejected at trace time."""
    import jax

    from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
        init_transformer,
    )

    cfg = TransformerConfig(d_model=32, n_heads=2, n_layers=2, d_ff=64, max_len=32)
    key = jax.random.PRNGKey(0)
    params = init_transformer(key, cfg)
    tokens = jax.random.randint(key, (8, 17), 0, cfg.vocab)

    oi1, s1 = make_lm_train_step(cfg, lr=1e-2)
    oi4, s4 = make_lm_train_step(cfg, lr=1e-2, accum_steps=4)
    p1, _, l1 = s1(params, oi1(params), tokens)
    p4, _, l4 = s4(params, oi4(params), tokens)
    np.testing.assert_allclose(float(l4), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p4), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="not divisible by accum_steps"):
        make_lm_train_step(cfg, accum_steps=3)[1](params, oi1(params), tokens)
    with pytest.raises(ValueError, match="accum_steps"):
        make_lm_train_step(cfg, accum_steps=0)


def test_mixed_precision_master_weights():
    """compute_dtype=bf16: forward/backward in bfloat16, params and
    optimizer stay fp32 (master weights) — converges on the pattern task."""
    import jax

    from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
        init_transformer,
    )

    cfg = TransformerConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=64)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    pattern = jnp.tile(jnp.arange(8, dtype=jnp.int32), 9)[None, :65].repeat(4, 0)
    oi, step = make_lm_train_step(cfg, lr=3e-3, compute_dtype=jnp.bfloat16)
    opt = oi(params)
    first = None
    for _ in range(30):
        params, opt, loss = step(params, opt, pattern)
        first = float(loss) if first is None else first
    assert float(loss) < first * 0.5
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.float32  # masters never degrade to bf16

"""Deploy-and-collect executor tests (2_final_multi_machine.sh analogue).

The real-cluster paths (ssh/rsync) are exercised as rendered dry-run
commands; execution is validated on the degenerate localhost cluster —
the same single-machine stand-in the reference uses (`mpirun
--oversubscribe`, SURVEY §4.4), but through the actual gRPC-coordinated
multi-process runtime.
"""

import socket
from pathlib import Path

from cuda_mpi_gpu_cluster_programming_tpu.parallel import deploy
from cuda_mpi_gpu_cluster_programming_tpu.parallel.distributed import ClusterConfig


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_dry_run_renders_ssh_and_executes_nothing(tmp_path, capsys):
    cluster = ClusterConfig.parse(["myko@gpu-a sm_86", "myko@gpu-b sm_50"])
    results = deploy.deploy_and_collect(
        cluster,
        "cuda_mpi_gpu_cluster_programming_tpu.parallel.distributed",
        workdir="/opt/work",
        log_root=str(tmp_path),
        dry_run=True,
    )
    out = capsys.readouterr().out
    assert "ssh myko@gpu-b" in out
    assert "JAX_PROCESS_ID=1" in out
    assert all(r.status == deploy.SKIPPED for r in results)
    assert not list(tmp_path.iterdir())  # nothing executed, no session dir


def test_reachability_local_and_dry_remote():
    cluster = ClusterConfig.parse(["localhost", "myko@far-host"])
    checks = deploy.check_reachable(cluster, dry_run=True)
    assert checks[0] == ("localhost", True, "local")
    host, ok, msg = checks[1]
    assert host == "far-host" and ok and msg.startswith("DRY: ssh")


def test_sync_code_local_copytree(tmp_path):
    src = tmp_path / "src"
    (src / "pkg").mkdir(parents=True)
    (src / "pkg" / "a.py").write_text("x = 1\n")
    (src / "__pycache__").mkdir()
    (src / "__pycache__" / "junk.pyc").write_text("junk")
    dst = tmp_path / "dst"
    cluster = ClusterConfig.parse(["localhost"])
    actions = deploy.sync_code(cluster, str(src), str(dst))
    assert actions[0][1].startswith("copytree")
    assert (dst / "pkg" / "a.py").read_text() == "x = 1\n"
    assert not (dst / "__pycache__").exists()  # excluded


def test_sync_in_place_skips(tmp_path):
    cluster = ClusterConfig.parse(["localhost"])
    actions = deploy.sync_code(cluster, str(tmp_path), str(tmp_path))
    assert "in-place" in actions[0][1]


def test_parse_log():
    verdict, ms = deploy._parse_log(
        "pid=0: psum=10.0 expect=10.0 -> PASSED\n"
        "AlexNet TPU Forward Pass completed in 12.500 ms\n"
    )
    assert verdict == "PASSED" and ms == 12.5
    assert deploy._parse_log("no contract lines")[0] == ""


def test_localhost_cluster_end_to_end(tmp_path):
    """One command deploys a 2-host (degenerate: both local) inventory,
    collects per-host logs, and parses the self-verification verdicts."""
    cluster = ClusterConfig.parse(["localhost", "127.0.0.1"], port=_free_port())
    results = deploy.deploy_and_collect(
        cluster,
        "cuda_mpi_gpu_cluster_programming_tpu.parallel.distributed",
        workdir=str(Path(__file__).resolve().parent.parent),
        log_root=str(tmp_path),
        timeout_s=240.0,
        extra_env={
            "PALLAS_AXON_POOL_IPS": "",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )
    assert [r.status for r in results] == [deploy.OK, deploy.OK], [
        (r.status, r.tail) for r in results
    ]
    assert all(r.verdict == "PASSED" for r in results)
    for r in results:
        text = Path(r.log_file).read_text()
        assert "global_devices=4" in text  # 2 procs x 2 virtual devices
    session_dirs = list(tmp_path.iterdir())
    assert len(session_dirs) == 1
    summary = (session_dirs[0] / "summary.csv").read_text()
    assert summary.count("OK") == 2

    # the session CSV follows the analysis contract: it ingests like any
    # harness session (deploy.py docstring promise)
    from cuda_mpi_gpu_cluster_programming_tpu import analysis

    conn = analysis.connect(tmp_path / "w.sqlite")
    analysis.cmd_ingest(conn, tmp_path, None)
    rows = conn.execute(
        "SELECT variant, status FROM summary_runs ORDER BY rowid"
    ).fetchall()
    assert len(rows) == 2
    assert all(v == "MultiHost distributed" and s == "OK" for v, s in rows)
    conn.close()

"""KV-cache incremental decode: parity with the training forward + generation.

The contract: ``decode_logits`` (one token at a time through per-layer
K/V caches) must reproduce ``forward_lm``'s logits — the same model, two
execution schedules. Generation is then argmax/sampling over that
verified path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
    TransformerConfig,
    decode_logits,
    forward_lm,
    generate,
    init_transformer,
    make_lm_train_step,
)

CFG = TransformerConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=96)


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    return init_transformer(key, CFG), jax.random.randint(key, (2, 40), 0, CFG.vocab)


def test_teacher_forced_parity(setup):
    params, tokens = setup
    lg_dec = decode_logits(params, tokens, CFG)
    lg_ref = forward_lm(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(lg_dec), np.asarray(lg_ref), rtol=1e-4, atol=2e-4
    )


def test_parity_bf16(setup):
    """bf16 params: the two schedules round differently (full-sequence
    matmuls vs per-token cache matmuls), so parity is loose — bf16 has
    ~2-3 significant decimal digits and the residual stream compounds it."""
    params, tokens = setup
    pb = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
    lg_dec = decode_logits(pb, tokens, CFG)
    lg_ref = forward_lm(pb, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(lg_dec, np.float32), np.asarray(lg_ref, np.float32),
        rtol=0.1, atol=0.3,
    )


def test_greedy_generation_continues_learned_pattern(setup):
    params, _ = setup
    pattern = jnp.tile(jnp.arange(8, dtype=jnp.int32), 12)[None, :65].repeat(4, 0)
    oi, step = make_lm_train_step(CFG, lr=3e-3)
    opt = oi(params)
    for _ in range(60):
        params, opt, _ = step(params, opt, pattern)
    prompt = pattern[:1, :16]
    seq = jax.jit(lambda p, pr: generate(p, pr, CFG, steps=24))(params, prompt)
    assert seq.shape == (1, 40)
    np.testing.assert_array_equal(np.asarray(seq[0, :16]), np.asarray(prompt[0]))
    want = (jnp.arange(16, 40) % 8).astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(seq[0, 16:]), np.asarray(want))


def test_sampling_and_guards(setup):
    params, tokens = setup
    # temperature sampling runs and stays in-vocab
    seq = generate(
        params, tokens[:, :8], CFG, steps=4, temperature=0.8,
        key=jax.random.PRNGKey(1),
    )
    assert seq.shape == (2, 12)
    assert int(seq.min()) >= 0 and int(seq.max()) < CFG.vocab
    with pytest.raises(ValueError, match="needs an explicit key"):
        generate(params, tokens[:, :8], CFG, steps=2, temperature=0.5)
    with pytest.raises(ValueError, match="steps"):
        generate(params, tokens[:, :8], CFG, steps=0)
    with pytest.raises(ValueError, match="max_len"):
        generate(params, tokens, CFG, steps=CFG.max_len)
def test_moe_teacher_forced_parity():
    """MoE serving (round-4 verdict weak item 6): the capacity-∞ decode
    FFN must reproduce forward_lm exactly whenever training routing drops
    nothing — pinned with an undroppable capacity factor (cap >= T for
    every expert), where the two schedules are the same math."""
    moe = TransformerConfig(
        d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=64,
        n_experts=4, capacity_factor=16.0,
    )
    key = jax.random.PRNGKey(3)
    params = init_transformer(key, moe)
    tokens = jax.random.randint(key, (2, 24), 0, moe.vocab)
    lg_dec = decode_logits(params, tokens, moe)
    lg_ref = forward_lm(params, tokens, moe)
    np.testing.assert_allclose(
        np.asarray(lg_dec), np.asarray(lg_ref), rtol=1e-4, atol=2e-4
    )


def test_moe_generation_runs():
    """generate() on an MoE config (default capacity factor): in-vocab
    tokens of the right shape through the capacity-∞ serving path."""
    moe = TransformerConfig(
        d_model=64, n_heads=2, n_layers=1, d_ff=128, max_len=64, n_experts=2
    )
    key = jax.random.PRNGKey(2)
    params = init_transformer(key, moe)
    prompt = jax.random.randint(key, (2, 8), 0, moe.vocab)
    seq = generate(params, prompt, moe, steps=4)
    assert seq.shape == (2, 12)
    assert int(seq.min()) >= 0 and int(seq.max()) < moe.vocab
    np.testing.assert_array_equal(np.asarray(seq[:, :8]), np.asarray(prompt))


def test_generate_with_tp_sharded_params():
    """Serving under tensor parallelism: generate() with Megatron-TP-sharded
    params (8-way) produces exactly the replicated sequence — GSPMD
    partitions the decode einsums with no decode-specific code."""
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.tensor_parallel import (
        shard_lm_params_tp,
    )

    key = jax.random.PRNGKey(5)
    params = init_transformer(key, CFG)
    prompt = jax.random.randint(key, (2, 8), 0, CFG.vocab)
    ref = np.asarray(generate(params, prompt, CFG, steps=12))
    tp_params = shard_lm_params_tp(params, make_mesh(8, axis_name="tp"))
    got = np.asarray(
        jax.jit(lambda p, pr: generate(p, pr, CFG, steps=12))(tp_params, prompt)
    )
    np.testing.assert_array_equal(got, ref)


def test_decode_bench_script_smoke():
    """scripts/decode_bench.py emits well-formed JSON rows on the CPU
    backend (the chip queue runs the same script for the serving tok/s
    evidence; this guards the script's import path and schema)."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    from cuda_mpi_gpu_cluster_programming_tpu.utils.env_info import (
        cpu_subprocess_env)

    root = Path(__file__).resolve().parent.parent
    out = subprocess.run(
        [sys.executable, str(root / "scripts" / "decode_bench.py"),
         "--batches", "1", "--steps", "4", "--repeats", "1"],
        capture_output=True, text=True, timeout=300,
        cwd=root,
        # CPU-forced child (single home for the axon-sitecustomize
        # gotchas) — the ambient TPU registration would make this test
        # hang whenever the tunnel is wedged.
        env=cpu_subprocess_env(1),
    )
    assert out.returncode == 0, out.stderr[-800:]
    rows = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    assert len(rows) == 1
    r = rows[0]
    assert r["metric"] == "lm_decode_tok_per_sec"
    assert r["batch"] == 1 and r["steps"] == 4
    assert r["tok_s"] > 0 and r["ms_per_step"] > 0

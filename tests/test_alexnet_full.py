"""Full-AlexNet tests: dims, blocks12-prefix equivalence, tier equivalence,
sharded spatial part, softmax head.

The extension task of README.md:19 with dims from summary.md:29-45.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.configs import REGISTRY, build_forward
from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import BLOCKS12, forward_blocks12
from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet_full import (
    ALEXNET,
    AlexNetConfig,
    forward_alexnet,
    forward_spatial,
    init_full_deterministic,
    init_full_random,
    predict,
    spatial_output_shape,
)

# Small config for CPU speed: 99 -> conv1 23 -> pool1 11 -> conv2 11 ->
# pool2 5 -> conv3/4/5 5 -> pool5 2.
SMALL = AlexNetConfig(
    blocks12=dataclasses.replace(BLOCKS12, in_height=99, in_width=99),
    fc6=64,
    fc7=32,
    num_classes=10,
)


def _x(batch=1, cfg=SMALL):
    return jax.random.uniform(
        jax.random.PRNGKey(0), (batch, cfg.in_height, cfg.in_width, cfg.in_channels)
    )


def test_spatial_dims_match_reference_table():
    # summary.md:29-45 dim chain: 227 -> ... -> 6x6x256
    assert spatial_output_shape(ALEXNET) == (6, 6, 256)
    assert spatial_output_shape(SMALL) == (2, 2, 256)


def test_full_param_shapes():
    params = init_full_deterministic(ALEXNET)
    assert params["conv3"]["w"].shape == (3, 3, 256, 384)
    assert params["conv4"]["w"].shape == (3, 3, 384, 384)
    assert params["conv5"]["w"].shape == (3, 3, 384, 256)
    assert params["fc6"]["w"].shape == (6 * 6 * 256, 4096)
    assert params["fc8"]["w"].shape == (4096, 1000)


def test_blocks12_prefix_bit_identical():
    """forward_spatial == conv3..pool5 applied on top of forward_blocks12 —
    i.e. the Blocks 1-2 prefix keeps the reference's exact semantics and
    golden oracle."""
    from cuda_mpi_gpu_cluster_programming_tpu.ops import reference as ops

    full_params = init_full_random(jax.random.PRNGKey(1), SMALL)
    x = _x()
    b12_params = {"conv1": full_params["conv1"], "conv2": full_params["conv2"]}
    want = forward_blocks12(b12_params, x, SMALL.blocks12)
    for name, spec in (("conv3", SMALL.conv3), ("conv4", SMALL.conv4), ("conv5", SMALL.conv5)):
        want = ops.relu(
            ops.conv2d(
                want,
                full_params[name]["w"],
                full_params[name]["b"],
                stride=spec.stride,
                padding=spec.padding,
            )
        )
    want = ops.maxpool(want, window=SMALL.pool5.window, stride=SMALL.pool5.stride)
    got = forward_spatial(full_params, x, SMALL)
    assert jnp.array_equal(got, want)


def test_logits_shape_and_softmax():
    params = init_full_random(jax.random.PRNGKey(2), SMALL)
    logits = jax.jit(lambda p, x: forward_alexnet(p, x, SMALL))(params, _x(3))
    assert logits.shape == (3, 10)
    probs = predict(params, _x(3), SMALL)
    np.testing.assert_allclose(np.asarray(probs.sum(axis=-1)), np.ones(3), rtol=1e-5)


def test_dropout_train_vs_eval():
    params = init_full_random(jax.random.PRNGKey(3), SMALL)
    x = _x()
    eval_logits = forward_alexnet(params, x, SMALL)
    train_logits = forward_alexnet(params, x, SMALL, dropout_key=jax.random.PRNGKey(0))
    assert not jnp.allclose(eval_logits, train_logits)  # dropout active
    eval2 = forward_alexnet(params, x, SMALL)
    assert jnp.array_equal(eval_logits, eval2)  # eval deterministic


def test_pallas_tier_matches_reference_tier():
    from cuda_mpi_gpu_cluster_programming_tpu.ops.pallas_model import forward_alexnet_pallas

    params = init_full_random(jax.random.PRNGKey(4), SMALL)
    x = _x(2)
    want = jax.jit(lambda p, x: forward_alexnet(p, x, SMALL))(params, x)
    got = jax.jit(lambda p, x: forward_alexnet_pallas(p, x, SMALL))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4)


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_sharded_full_matches_single(n_shards):
    """Row-sharded spatial + replicated FC == single-device full pass, even
    when late layers leave some shards owning zero rows."""
    cfg = REGISTRY["v6_full_sharded"]
    params = init_full_random(jax.random.PRNGKey(5), SMALL)
    x = _x(2)
    want = jax.jit(lambda p, x: forward_alexnet(p, x, SMALL))(params, x)
    fwd = build_forward(cfg, SMALL, n_shards=n_shards)
    got = fwd(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4)


def test_full_deterministic_cross_tier_exact():
    """Deterministic init: pallas and reference tiers agree to float tolerance
    on the full net (the reference never achieved V3==V1 comparability)."""
    from cuda_mpi_gpu_cluster_programming_tpu.ops.pallas_model import forward_alexnet_pallas

    params = init_full_deterministic(SMALL)
    x = jnp.ones((1, SMALL.in_height, SMALL.in_width, SMALL.in_channels))
    a = jax.jit(lambda p, x: forward_alexnet(p, x, SMALL))(params, x)
    b = jax.jit(lambda p, x: forward_alexnet_pallas(p, x, SMALL))(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_full_model_hpool_fusion_bitwise():
    """fuse="hpool" on the FULL model (conv1/conv2/conv5 -> pool
    adjacencies via the chain walker) is bitwise identical to unfused —
    the blocks12 equality test can't see the conv5->pool5 adjacency or
    the walker's skip-next bookkeeping. Variants passed explicitly (jit
    cache footgun; see test_bit_exact's g8 probe)."""
    from cuda_mpi_gpu_cluster_programming_tpu.ops import pallas_kernels as pk
    from cuda_mpi_gpu_cluster_programming_tpu.ops.pallas_model import (
        forward_alexnet_pallas)

    params = init_full_random(jax.random.PRNGKey(11), SMALL)
    x = _x(2)
    base = np.asarray(
        forward_alexnet_pallas(params, x, SMALL, variants=pk.KernelVariants())
    )
    fused = np.asarray(
        forward_alexnet_pallas(
            params, x, SMALL, variants=pk.KernelVariants(fuse="hpool")
        )
    )
    np.testing.assert_array_equal(base, fused)

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.ops import conv2d, lrn, maxpool, relu

from oracle import conv2d_np, lrn_np, maxpool_np


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(485)


def test_conv2d_vs_oracle(rng):
    x = rng.standard_normal((9, 9, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 5)).astype(np.float32)
    b = rng.standard_normal(5).astype(np.float32)
    got = conv2d(jnp.asarray(x)[None], jnp.asarray(w), jnp.asarray(b), stride=2, padding=1)[0]
    want = conv2d_np(x, w, b, stride=2, padding=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_conv2d_no_padding(rng):
    x = rng.standard_normal((11, 11, 2)).astype(np.float32)
    w = rng.standard_normal((5, 5, 2, 4)).astype(np.float32)
    b = np.zeros(4, np.float32)
    got = conv2d(jnp.asarray(x)[None], jnp.asarray(w), jnp.asarray(b), stride=4, padding=0)[0]
    want = conv2d_np(x, w, b, stride=4, padding=0)
    assert got.shape == (2, 2, 4)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_relu():
    x = jnp.array([[-1.0, 0.0, 2.5]])
    np.testing.assert_array_equal(relu(x), jnp.array([[0.0, 0.0, 2.5]]))


def test_maxpool_vs_oracle(rng):
    x = rng.standard_normal((7, 7, 4)).astype(np.float32)
    got = maxpool(jnp.asarray(x)[None], window=3, stride=2)[0]
    want = maxpool_np(x, window=3, stride=2)
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("alpha_over_size", [False, True])
def test_lrn_vs_oracle(rng, alpha_over_size):
    x = rng.standard_normal((4, 4, 8)).astype(np.float32)
    got = lrn(jnp.asarray(x)[None], size=5, alpha=1e-4, beta=0.75, k=2.0, alpha_over_size=alpha_over_size)[0]
    want = lrn_np(x, size=5, alpha=1e-4, beta=0.75, k=2.0, alpha_over_size=alpha_over_size)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_edge_truncation():
    # channel 0's window is [0..2] for size=5: denominator uses only 3 values
    x = np.ones((1, 1, 6), np.float32)
    got = np.asarray(
        lrn(jnp.asarray(x)[None], size=5, alpha=0.5, beta=1.0, k=1.0, alpha_over_size=True)[0]
    )
    want = lrn_np(x, size=5, alpha=0.5, beta=1.0, k=1.0, alpha_over_size=True)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert got[0, 0, 0] == pytest.approx(1.0 / (1.0 + 0.1 * 3))
    assert got[0, 0, 2] == pytest.approx(1.0 / (1.0 + 0.1 * 5))


def test_batch_axis(rng):
    x = rng.standard_normal((2, 9, 9, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 5)).astype(np.float32)
    b = rng.standard_normal(5).astype(np.float32)
    batched = conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), stride=1, padding=1)
    for n in range(2):
        single = conv2d(jnp.asarray(x[n])[None], jnp.asarray(w), jnp.asarray(b), stride=1, padding=1)[0]
        np.testing.assert_allclose(batched[n], single, rtol=1e-6)

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import BLOCKS12
from cuda_mpi_gpu_cluster_programming_tpu.models.init import init_params_deterministic
from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh
from cuda_mpi_gpu_cluster_programming_tpu.training import make_train_step

CFG = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)


def _data(batch=4):
    key = jax.random.PRNGKey(7)
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, 63, 63, 3), jnp.float32)
    y = jax.random.uniform(ky, (batch, 2, 2, 256), jnp.float32)
    return x, y


def test_loss_decreases_single_device():
    params = init_params_deterministic(CFG)
    x, y = _data()
    opt_init, step = make_train_step(CFG, mesh=None, lr=1e-4)
    opt_state = opt_init(params)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_stateful_optimizer_momentum_actually_accumulates():
    """Momentum state must thread through steps (regression for a bug where
    opt state was re-initialized every step, silently degrading to plain SGD)."""
    import optax

    params = init_params_deterministic(CFG)
    x, y = _data()
    opt_init, step = make_train_step(CFG, mesh=None, optimizer=optax.sgd(1e-4, momentum=0.9))
    opt_state = opt_init(params)
    # two momentum steps
    p, s, _ = step(params, opt_state, x, y)
    p, s, _ = step(p, s, x, y)
    # two plain-SGD steps
    opt_init2, step2 = make_train_step(CFG, mesh=None, lr=1e-4)
    q, t, _ = step2(params, opt_init2(params), x, y)
    q, t, _ = step2(q, t, x, y)
    # momentum's second step must differ from plain SGD's
    a = np.asarray(p["conv1"]["w"])
    b = np.asarray(q["conv1"]["w"])
    assert np.abs(a - b).max() > 0


def test_spatial_parallel_training_matches_unsharded():
    """sp (context-parallel) training through shard_map must reproduce the
    single-device gradients — the capability GSPMD autodiff gets wrong."""
    x, y = _data()
    p0 = init_params_deterministic(CFG)
    i1, s1 = make_train_step(CFG, mesh=None, lr=1e-4)
    i2, s2 = make_train_step(CFG, lr=1e-4, sp_shards=4)
    p1, _, l1 = s1(p0, i1(p0), x, y)
    p2, _, l2 = s2(p0, i2(p0), x, y)
    assert np.isclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7)


def test_sharded_step_matches_unsharded():
    """dp-sharded training step must agree with the single-device step.

    (H-axis "sp" annotation is deliberately NOT applied in training: GSPMD
    conv weight-grads under spatial sharding are wrong in this JAX build —
    see training.x_spec. The mesh still carries an sp axis to prove the
    step tolerates one.)
    """
    mesh = make_mesh(4, dp=2)
    x, y = _data()
    p0 = init_params_deterministic(CFG)
    i1, s1 = make_train_step(CFG, mesh=None, lr=1e-4)
    i2, s2 = make_train_step(CFG, mesh=mesh, lr=1e-4)
    p1, _, l1 = s1(p0, i1(p0), x, y)
    p2, _, l2 = s2(p0, i2(p0), x, y)
    assert np.isclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_tensor_parallel_training_matches_unsharded():
    """TP (K-axis filter decomposition) training must reproduce the
    single-device gradients: all_gather/channel-ppermute transposes are
    exact, so one SGD step agrees with the unsharded step."""
    import pytest

    x, y = _data()
    p0 = init_params_deterministic(CFG)
    i1, s1 = make_train_step(CFG, mesh=None, lr=1e-4)
    i2, s2 = make_train_step(CFG, lr=1e-4, tp_shards=8)
    p1, _, l1 = s1(p0, i1(p0), x, y)
    p2, _, l2 = s2(p0, i2(p0), x, y)
    assert np.isclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_train_step(CFG, sp_shards=2, tp_shards=2)


class TestFullAlexNetClassifier:
    """Full-net classification training (the extension task trainable)."""

    def _setup(self):
        import dataclasses

        import jax

        from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import BLOCKS12
        from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet_full import (
            AlexNetConfig,
            init_full_random,
        )

        # 99x99 is the smallest convenient input where pool5 stays
        # non-degenerate (99 -> 23 -> 11 -> 5 -> 2 through the pools).
        cfg = AlexNetConfig(
            blocks12=dataclasses.replace(BLOCKS12, in_height=99, in_width=99),
            fc6=64, fc7=32, num_classes=4,
        )
        params = init_full_random(jax.random.PRNGKey(0), cfg)
        x = jax.random.uniform(jax.random.PRNGKey(1), (4, 99, 99, 3))
        labels = jax.numpy.asarray([0, 1, 2, 3])
        return cfg, params, x, labels

    def test_memorizes_four_samples(self):
        import jax

        from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet_full import predict
        from cuda_mpi_gpu_cluster_programming_tpu.training import (
            make_classifier_train_step,
        )

        cfg, params, x, labels = self._setup()
        opt_init, step = make_classifier_train_step(cfg, lr=1e-3)
        opt_state = opt_init(params)
        first = None
        for _ in range(80):
            params, opt_state, loss = step(params, opt_state, x, labels)
            if first is None:
                first = float(loss)
        assert float(loss) < min(0.2, first), (first, float(loss))
        preds = jax.numpy.argmax(predict(params, x, cfg), axis=-1)
        assert (preds == labels).all(), preds

    def test_dp_mesh_classifier(self):
        from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh
        from cuda_mpi_gpu_cluster_programming_tpu.training import (
            make_classifier_train_step,
        )

        cfg, params, x, labels = self._setup()
        mesh = make_mesh(2, dp=4)  # ("dp","sp") — batch over dp
        opt_init, step = make_classifier_train_step(cfg, mesh=mesh, lr=1e-3)
        opt_state = opt_init(params)
        l0 = None
        # Multi-step: a single adam step at fresh-moment estimates can
        # overshoot; convergence over a few steps is the real contract.
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, x, labels)
            l0 = float(loss) if l0 is None else l0
        assert float(loss) < l0, (l0, float(loss))

    def test_remat_matches_plain(self):
        import numpy as np

        from cuda_mpi_gpu_cluster_programming_tpu.training import (
            make_classifier_train_step,
        )

        cfg, params, x, labels = self._setup()
        opt_init, step_plain = make_classifier_train_step(cfg, lr=1e-3)
        _, step_remat = make_classifier_train_step(cfg, lr=1e-3, remat=True)
        s = opt_init(params)
        _, _, l_plain = step_plain(params, s, x, labels)
        _, _, l_remat = step_remat(params, s, x, labels)
        np.testing.assert_allclose(float(l_remat), float(l_plain), rtol=1e-6)

"""Environment capture tests (pc_v4_environment_info.txt analogue)."""

import json
from pathlib import Path

import pytest

from cuda_mpi_gpu_cluster_programming_tpu.utils.env_info import collect, main

REQUIREMENTS = Path(__file__).resolve().parents[1] / "requirements.txt"


def test_collect_pins_match_requirements():
    info = collect(probe_devices=False)
    assert info["packages"]["jax"] is not None
    with open(REQUIREMENTS) as f:
        pins = dict(
            line.strip().split("==")
            for line in f
            if "==" in line and not line.startswith("#")
        )
    installed_jax = info["packages"].get("jax")
    if installed_jax != pins.get("jax"):
        # The pins describe the TPU VM toolchain the framework is
        # benchmarked against (requirements.txt header); a CI container
        # baking a different jax is an environment property, not a repo
        # regression — skip ATTRIBUTABLY (both versions named) instead of
        # failing every tier-1 sweep on a container it cannot change.
        pytest.skip(
            f"not the pinned TPU VM toolchain: installed jax {installed_jax}, "
            f"requirements.txt pins {pins.get('jax')} — pin drift is a "
            "container property; env_info still captures it for the record"
        )
    for pkg, pinned in pins.items():
        if pkg in ("pytest",):  # test-only tooling may drift
            continue
        assert info["packages"].get(pkg) == pinned, f"{pkg} drifted from requirements.txt"


def test_collect_device_probe():
    info = collect(probe_devices=True)
    assert info["device_count"] == 8  # conftest virtual mesh
    assert info["backend"] == "cpu"


def test_cli_writes_json(tmp_path, capsys):
    out = tmp_path / "env.json"
    assert main(["--out", str(out), "--no-devices"]) == 0
    data = json.loads(out.read_text())
    assert "packages" in data and "python" in data
    assert json.loads(capsys.readouterr().out) == data

"""Property-based fuzzing of the Pallas kernels vs the XLA reference ops.

The hand-written kernels are the riskiest numerics in the framework (the
VMEM-OOM and bf16-reshape failures this round were both geometry-dependent
— found only when the real chip saw new shapes). These tests sweep random
geometry x stride x padding x variant through the interpreter-mode kernels
against `ops.reference`, so geometry edge cases (leftover rows, prime
dims, W-alignment padding, fq boundaries) are searched instead of
hand-picked. Deadlines are disabled: interpreter-mode pallas_call tracing
is slow and measured in seconds, not milliseconds.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# requirements.txt pins hypothesis, but containers built without dev extras
# must still COLLECT cleanly — skip this module instead of erroring the
# whole tier-1 collection.
pytest.importorskip("hypothesis", reason="hypothesis not installed")
from hypothesis import assume, given, settings, strategies as st  # noqa: E402

from cuda_mpi_gpu_cluster_programming_tpu.ops import reference as ops
from cuda_mpi_gpu_cluster_programming_tpu.ops import pallas_kernels as pk

_SETTINGS = dict(max_examples=12, deadline=None, derandomize=True)


def _rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@settings(**_SETTINGS)
@given(
    h=st.integers(7, 33),
    w_dim=st.integers(7, 33),
    c=st.sampled_from([1, 3, 4]),
    k=st.sampled_from([4, 8]),
    f=st.sampled_from([3, 5, 7]),
    stride=st.integers(1, 4),
    padding=st.integers(0, 3),
    relu=st.booleans(),
    variant=st.sampled_from(["taps", "fused", "vcol", "pairs", "g8"]),
)
def test_conv_matches_reference(h, w_dim, c, k, f, stride, padding, relu, variant):
    # Reject (regenerate) degenerate geometries instead of silently
    # passing; unreachable with today's ranges, load-bearing if widened.
    assume(h + 2 * padding >= f and w_dim + 2 * padding >= f)
    # Plain env set/restore per example (hypothesis rejects function-scoped
    # fixtures; the variant env is read at trace time of the direct call).
    saved = os.environ.get("TPU_FRAMEWORK_CONV")  # noqa: variant-env
    os.environ["TPU_FRAMEWORK_CONV"] = variant
    try:
        _check_conv(h, w_dim, c, k, f, stride, padding, relu)
    finally:
        if saved is None:
            os.environ.pop("TPU_FRAMEWORK_CONV", None)
        else:
            os.environ["TPU_FRAMEWORK_CONV"] = saved


def _check_conv(h, w_dim, c, k, f, stride, padding, relu):
    x = _rand(h * 31 + w_dim, (1, h, w_dim, c))
    w = _rand(f, (f, f, c, k)) * 0.2
    b = _rand(k, (k,)) * 0.1
    got = np.asarray(pk.conv2d_pallas(x, w, b, stride=stride, padding=padding, relu=relu))
    want = np.asarray(ops.conv2d(x, w, b, stride=stride, padding=padding))
    if relu:
        want = np.maximum(want, 0.0)
    assert got.shape == want.shape, (got.shape, want.shape)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@settings(**_SETTINGS)
@given(
    h=st.integers(4, 30),
    w_dim=st.integers(4, 30),
    c=st.sampled_from([1, 8, 16]),
    window=st.sampled_from([2, 3]),
    stride=st.integers(1, 3),
)
def test_maxpool_matches_reference(h, w_dim, c, window, stride):
    assume(h >= window and w_dim >= window)
    x = _rand(h * 37 + w_dim, (2, h, w_dim, c))
    got = np.asarray(pk.maxpool_pallas(x, window=window, stride=stride))
    want = np.asarray(ops.maxpool(x, window=window, stride=stride))
    np.testing.assert_array_equal(got, want)  # max is exact


@settings(**_SETTINGS)
@given(
    c=st.sampled_from([4, 16, 32]),
    size=st.sampled_from([3, 5]),
    aos=st.booleans(),
)
def test_lrn_matches_reference(c, size, aos):
    x = _rand(c * 13 + size, (1, 6, 6, c))
    got = np.asarray(
        pk.lrn_pallas(x, size=size, alpha=1e-4, beta=0.75, k=2.0, alpha_over_size=aos)
    )
    want = np.asarray(
        ops.lrn(x, size=size, alpha=1e-4, beta=0.75, k=2.0, alpha_over_size=aos)
    )
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)



"""staticcheck engine + rule coverage.

Three layers per new rule: trigger on a fixture (exactly one finding with
the expected code — the seeded self-check), suppression via ``# noqa``, and
suppression via the committed-baseline mechanism. Engine features (noqa
span resolution, ``# noqa-file`` pragma, baseline semantics, json output)
get their own cases. The legacy rule set keeps its coverage in
tests/test_lint.py against the CLI shim.
"""

import io
import json
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from cuda_mpi_gpu_cluster_programming_tpu.staticcheck import engine  # noqa: E402
from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.engine import (  # noqa: E402
    baseline_payload,
    check_files,
    split_by_baseline,
)


def findings_for(path: Path, code: str = None):
    out, _ = check_files([path])
    return [f for f in out if code is None or f.code == code]


def run_engine(paths, baseline_path=None, fmt="text", update=False):
    buf = io.StringIO()
    rc = engine.run(
        paths, baseline_path=baseline_path, fmt=fmt,
        update_baseline=update, out=buf,
    )
    return rc, buf.getvalue()


# ---------------------------------------------------------------------------
# rule fixtures: (filename, source, expected-code, expected-line)

_WRONG_AXIS = (
    "wrongaxis.py",
    "from jax import lax, shard_map\n"
    "from jax.sharding import PartitionSpec as P\n"
    "def body(x):\n"
    "    return lax.psum(x, 'dp')\n"          # mesh below binds only 'sp'
    "def build(mesh):\n"
    "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
    "                     out_specs=P('sp'))\n",
    "collective-axis",
    4,
)
_UNREDUCED = (
    "unreduced.py",
    "import jax.numpy as jnp\n"
    "from jax import shard_map\n"
    "from jax.sharding import PartitionSpec as P\n"
    "def body(a, b):\n"
    "    return jnp.matmul(a, b)\n"
    "def build(mesh):\n"
    "    return shard_map(body, mesh=mesh,\n"
    "                     in_specs=(P(None, 'tp'), P('tp', None)),\n"
    "                     out_specs=P())\n",
    "unreduced-contraction",
    7,
)
_HOST_SYNC = (
    "bench.py",  # the rule is scoped to the measurement surfaces by name
    "import time\n"
    "def measure(fn, x, steps):\n"
    "    times = []\n"
    "    for _ in range(steps):\n"
    "        t0 = time.perf_counter()\n"
    "        out = float(fn(x))\n"
    "        times.append(time.perf_counter() - t0)\n"
    "    return times, out\n",
    "host-sync-in-hot-loop",
    6,
)
_KEY_REUSE = (
    "keyreuse.py",
    "import jax\n"
    "def draws():\n"
    "    key = jax.random.PRNGKey(0)\n"
    "    a = jax.random.normal(key, (4,))\n"
    "    b = jax.random.normal(key, (4,))\n"
    "    return a, b\n",
    "key-reuse",
    5,
)
_JIT_IN_LOOP = (
    "jitloop.py",
    "import jax\n"
    "def sweep(fns, x):\n"
    "    outs = []\n"
    "    for fn in fns:\n"
    "        outs.append(jax.jit(fn)(x))\n"
    "    return outs\n",
    "jit-in-loop",
    5,
)
_VMA_OFF = (
    "vmaoff.py",
    "from jax import shard_map\n"
    "from jax.sharding import PartitionSpec as P\n"
    "def build(body, mesh):\n"
    "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
    "                     out_specs=P('sp'), check_vma=False)\n",
    "check-vma-disabled",
    5,
)
_STALE_DEVICES = (
    "staledev.py",
    "import jax\n"
    "from jax.sharding import Mesh\n"
    "DEVICES = jax.devices()\n"        # cached at import: stale by rebuild
    "def rebuild(n):\n"
    "    return Mesh(DEVICES[:n], ('sp',))\n",
    "stale-device-set",
    5,
)
ALL_FIXTURES = [
    _WRONG_AXIS, _UNREDUCED, _HOST_SYNC, _KEY_REUSE, _JIT_IN_LOOP, _VMA_OFF,
    _STALE_DEVICES,
]


@pytest.mark.parametrize(
    "name,src,code,line", ALL_FIXTURES, ids=[f[2] for f in ALL_FIXTURES]
)
def test_rule_triggers_exactly_once(tmp_path, name, src, code, line):
    """The seeded self-check: each planted bug yields exactly ONE finding
    with the expected code, at the expected line."""
    p = tmp_path / name
    p.write_text(src)
    got = findings_for(p, code)
    assert len(got) == 1, [f"{f.code}@{f.line}" for f in findings_for(p)]
    assert got[0].line == line
    assert got[0].severity == "error"


@pytest.mark.parametrize(
    "name,src,code,line", ALL_FIXTURES, ids=[f[2] for f in ALL_FIXTURES]
)
def test_rule_suppressed_by_noqa(tmp_path, name, src, code, line):
    lines = src.splitlines()
    lines[line - 1] += f"  # noqa: {code} deliberate (with a reason)"
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    assert findings_for(p, code) == []


@pytest.mark.parametrize(
    "name,src,code,line", ALL_FIXTURES, ids=[f[2] for f in ALL_FIXTURES]
)
def test_rule_grandfathered_by_baseline(tmp_path, name, src, code, line):
    p = tmp_path / name
    p.write_text(src)
    all_findings = findings_for(p)
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(baseline_payload(all_findings, ROOT)))
    rc, out = run_engine([p], baseline_path=bp)
    assert rc == 0, out
    assert f"[{code}]" not in out
    assert f"{len(all_findings)} baselined" in out


# ---------------------------------------------------------------------------
# negatives: working idioms must NOT be flagged


def test_collective_axis_bound_via_module_constant(tmp_path):
    p = tmp_path / "ok.py"
    p.write_text(
        "from jax import lax, shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "AXIS = 'sp'\n"
        "def body(x):\n"
        "    return lax.psum(x, AXIS)\n"
        "def build(mesh):\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P(None, AXIS),),\n"
        "                     out_specs=P(None, AXIS))\n"
    )
    assert findings_for(p, "collective-axis") == []


def test_collective_axis_dynamic_name_not_judged(tmp_path):
    # A variable axis (halo.py-style helper taking axis_name) is not
    # statically resolvable: never flagged.
    p = tmp_path / "helper.py"
    p.write_text(
        "from jax import lax\n"
        "def exchange(x, axis_name):\n"
        "    return lax.ppermute(x, axis_name, [(0, 1)])\n"
    )
    assert findings_for(p, "collective-axis") == []


def test_unreduced_contraction_ok_with_psum_or_out_axis(tmp_path):
    base = (
        "import jax.numpy as jnp\n"
        "from jax import lax, shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def body(a, b):\n"
        "    return {ret}\n"
        "def build(mesh):\n"
        "    return shard_map(body, mesh=mesh,\n"
        "                     in_specs=(P(None, 'tp'), P('tp', None)),\n"
        "                     out_specs={out})\n"
    )
    psum = tmp_path / "with_psum.py"
    psum.write_text(base.format(ret="lax.psum(jnp.matmul(a, b), 'tp')", out="P()"))
    assert findings_for(psum, "unreduced-contraction") == []
    kept = tmp_path / "axis_kept.py"
    kept.write_text(base.format(ret="jnp.matmul(a, b)", out="P(None, 'tp')"))
    assert findings_for(kept, "unreduced-contraction") == []


def test_host_sync_scoping(tmp_path):
    src = (
        "import time\n"
        "def f(rows):\n"
        "    for r in rows:\n"
        "        t0 = time.monotonic()\n"
        "        x = float(r)\n"
        "        _ = time.monotonic() - t0\n"
        "    return x\n"
    )
    # Same code outside the measurement surfaces: not in scope.
    other = tmp_path / "parsing.py"
    other.write_text(src)
    assert findings_for(other, "host-sync-in-hot-loop") == []
    # float() in an UNtimed loop (row parsing) is not flagged even in scope.
    untimed = tmp_path / "harness.py"
    untimed.write_text(
        "def f(rows):\n"
        "    out = [0.0]\n"
        "    for r in rows:\n"
        "        out.append(float(r))\n"
        "    return out\n"
    )
    assert findings_for(untimed, "host-sync-in-hot-loop") == []
    # .item() is a sync regardless of timing calls.
    item = tmp_path / "training.py"
    item.write_text(
        "def f(losses):\n"
        "    total = 0.0\n"
        "    for l in losses:\n"
        "        total += l.item()\n"
        "    return total\n"
    )
    assert len(findings_for(item, "host-sync-in-hot-loop")) == 1


def test_host_sync_off_timed_path_exemption(tmp_path):
    """The in-graph sentinel contract: digest screening inside a function
    decorated @off_timed_path is exempt (it is a host round trip BY DESIGN,
    between timed regions); the same sync undecorated still trips. Both in
    supervisor.py, which the rule now scopes alongside run.py."""
    f = tmp_path / "supervisor.py"
    f.write_text(
        "import numpy as np\n"
        "from cuda_mpi_gpu_cluster_programming_tpu.resilience.sentinel import (\n"
        "    off_timed_path,\n"
        ")\n"
        "@off_timed_path\n"
        "def screen(digests):\n"
        "    out = {}\n"
        "    for stage, vec in digests.items():\n"
        "        out[stage] = np.asarray(vec)\n"
        "    return out\n"
        "def hot(digests):\n"
        "    out = {}\n"
        "    for stage, vec in digests.items():\n"
        "        out[stage] = np.asarray(vec)\n"
        "    return out\n"
    )
    found = findings_for(f, "host-sync-in-hot-loop")
    assert len(found) == 1
    assert found[0].line == 14  # the undecorated copy only
    assert "off_timed_path" in found[0].message


def test_host_sync_scope_includes_run_and_supervisor():
    """run.py and resilience/supervisor.py are measurement surfaces now —
    and the shipped code stays clean under the grown scope (the repo-clean
    assertion for the in-graph taps)."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        HostSyncInHotLoopRule,
        _HOT_LOOP_FILES,
    )

    assert {"run.py", "supervisor.py"} <= _HOT_LOOP_FILES
    rule = HostSyncInHotLoopRule()
    assert rule.applies(Path("cuda_mpi_gpu_cluster_programming_tpu/run.py"))
    for rel in (
        "cuda_mpi_gpu_cluster_programming_tpu/run.py",
        "cuda_mpi_gpu_cluster_programming_tpu/resilience/supervisor.py",
        "cuda_mpi_gpu_cluster_programming_tpu/resilience/sentinel.py",
    ):
        assert findings_for(ROOT / rel, "host-sync-in-hot-loop") == []


def test_host_sync_scope_includes_serving_dispatch_loop(tmp_path):
    """ISSUE 6 satellite: the serving subsystem's dispatch/load loops are
    hot paths — a host sync per dispatched batch is a latency tax on every
    request — so serving/{server,loadgen,batcher,queue}.py are in scope,
    the shipped modules stay clean, and the @off_timed_path exemption
    (journal writes / result slicing) works there exactly as it does for
    the supervisor's screening."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        HostSyncInHotLoopRule,
        _HOT_LOOP_FILES,
    )

    assert {"server.py", "loadgen.py", "batcher.py", "queue.py"} <= _HOT_LOOP_FILES
    rule = HostSyncInHotLoopRule()
    assert rule.applies(
        Path("cuda_mpi_gpu_cluster_programming_tpu/serving/server.py")
    )
    for rel in (
        "cuda_mpi_gpu_cluster_programming_tpu/serving/server.py",
        "cuda_mpi_gpu_cluster_programming_tpu/serving/loadgen.py",
        "cuda_mpi_gpu_cluster_programming_tpu/serving/batcher.py",
        "cuda_mpi_gpu_cluster_programming_tpu/serving/queue.py",
    ):
        assert findings_for(ROOT / rel, "host-sync-in-hot-loop") == []
    # a sync in a dispatch loop IS flagged in a serving-named file...
    bad = tmp_path / "server.py"
    bad.write_text(
        "import numpy as np\n"
        "def loop(batches, fwd):\n"
        "    outs = []\n"
        "    for b in batches:\n"
        "        outs.append(np.asarray(fwd(b)))\n"
        "    return outs\n"
    )
    assert len(findings_for(bad, "host-sync-in-hot-loop")) == 1
    # ...and the same sync under @off_timed_path (journal/completion
    # writes) is exempt, per the existing annotation contract.
    ok = tmp_path / "loadgen.py"
    ok.write_text(
        "import numpy as np\n"
        "from cuda_mpi_gpu_cluster_programming_tpu.resilience.sentinel "
        "import off_timed_path\n"
        "@off_timed_path\n"
        "def complete(batches):\n"
        "    outs = []\n"
        "    for b in batches:\n"
        "        outs.append(np.asarray(b))\n"
        "    return outs\n"
    )
    assert findings_for(ok, "host-sync-in-hot-loop") == []


def test_host_sync_scope_includes_controller(tmp_path):
    """ISSUE 18 satellite: the Autopilot controller is evaluated from the
    dispatch loop's observation cadence every tick, so
    serving/controller.py joins the hot-loop scope — the shipped module
    stays clean (actuation rides @off_timed_path), a sync in an
    undecorated controller loop is flagged, and the decorated copy is
    exempt."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        HostSyncInHotLoopRule,
        _HOT_LOOP_FILES,
    )

    assert "controller.py" in _HOT_LOOP_FILES
    rule = HostSyncInHotLoopRule()
    assert rule.applies(
        Path("cuda_mpi_gpu_cluster_programming_tpu/serving/controller.py")
    )
    assert findings_for(
        ROOT / "cuda_mpi_gpu_cluster_programming_tpu/serving/controller.py",
        "host-sync-in-hot-loop",
    ) == []
    bad = tmp_path / "controller.py"
    bad.write_text(
        "import numpy as np\n"
        "def evaluate(windows, fwd):\n"
        "    burns = []\n"
        "    for w in windows:\n"
        "        burns.append(np.asarray(fwd(w)))\n"
        "    return burns\n"
    )
    assert len(findings_for(bad, "host-sync-in-hot-loop")) == 1
    (tmp_path / "ok").mkdir()
    ok = tmp_path / "ok" / "controller.py"
    ok.write_text(
        "import numpy as np\n"
        "from cuda_mpi_gpu_cluster_programming_tpu.resilience.sentinel "
        "import off_timed_path\n"
        "@off_timed_path\n"
        "def screen(windows):\n"
        "    burns = []\n"
        "    for w in windows:\n"
        "        burns.append(np.asarray(w))\n"
        "    return burns\n"
    )
    assert findings_for(ok, "host-sync-in-hot-loop") == []


def test_key_reuse_split_and_branches_ok(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import jax\n"
        "def draws(flag):\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    a = jax.random.normal(k1, (4,))\n"
        "    b = jax.random.normal(k2, (4,))\n"
        "    if flag:\n"
        "        c = jax.random.normal(b, (4,))\n"
        "    else:\n"
        "        c = jax.random.normal(b, (4,))\n"  # exclusive branch: fine
        "    return a, c\n"
    )
    assert findings_for(ok, "key-reuse") == []


def test_key_reuse_loop_invariant_key(tmp_path):
    p = tmp_path / "loop.py"
    p.write_text(
        "import jax\n"
        "def gen(n):\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    out = []\n"
        "    for _ in range(n):\n"
        "        out.append(jax.random.normal(key, (4,)))\n"
        "    return out\n"
    )
    assert len(findings_for(p, "key-reuse")) == 1
    ok = tmp_path / "loop_ok.py"
    ok.write_text(
        "import jax\n"
        "def gen(n):\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    out = []\n"
        "    for _ in range(n):\n"
        "        key, sub = jax.random.split(key)\n"
        "        out.append(jax.random.normal(sub, (4,)))\n"
        "    return out\n"
    )
    assert findings_for(ok, "key-reuse") == []


def test_jit_in_loop_hoisted_ok(tmp_path):
    p = tmp_path / "ok.py"
    p.write_text(
        "import jax\n"
        "def sweep(fn, xs):\n"
        "    jfn = jax.jit(fn)\n"
        "    return [jfn(x) for x in xs]\n"
    )
    assert findings_for(p, "jit-in-loop") == []


def test_check_vma_computed_value_ok(tmp_path):
    # check_vma=kernel_check_vma() (the sanctioned pattern) is not a
    # literal False: never flagged.
    p = tmp_path / "ok.py"
    p.write_text(
        "from jax import shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def build(body, mesh, flag):\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
        "                     out_specs=P('sp'), check_vma=flag)\n"
    )
    assert findings_for(p, "check-vma-disabled") == []


def test_stale_device_set_requery_and_module_scope_ok(tmp_path):
    """The sanctioned patterns stay silent: re-querying jax.devices() at
    build time inside the function, and a module-scope mesh build (runs at
    import, when the cached list is still fresh)."""
    p = tmp_path / "ok.py"
    p.write_text(
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "DEVICES = jax.devices()\n"
        "TOP_MESH = Mesh(DEVICES, ('sp',))\n"   # import-time: fresh
        "def rebuild(n):\n"
        "    return Mesh(jax.devices()[:n], ('sp',))\n"  # re-query: fresh
        "def helper(devs, n):\n"
        "    return Mesh(devs[:n], ('sp',))\n"  # caller-supplied: not judged
    )
    assert findings_for(p, "stale-device-set") == []


def test_stale_device_set_make_mesh_kwarg_and_list_wrap(tmp_path):
    """make_mesh(devices=CACHED) and list(jax.devices()) caches are the
    same bug in different spelling — both flagged."""
    p = tmp_path / "kw.py"
    p.write_text(
        "import jax\n"
        "from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh\n"
        "ALL = list(jax.devices())\n"
        "def retry_build(n):\n"
        "    return make_mesh(n, devices=ALL)\n"
    )
    found = findings_for(p, "stale-device-set")
    assert [f.line for f in found] == [5]
    assert "re-query" in found[0].message


def test_stale_device_set_annotated_module_cache_flagged(tmp_path):
    """ISSUE 10: the annotated spelling of the module cache
    (``DEVICES: list = jax.devices()``) is the same stale-device bug —
    flagged like the bare assignment."""
    p = tmp_path / "ann.py"
    p.write_text(
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "DEVICES: list = jax.devices()\n"
        "def rebuild(n):\n"
        "    return Mesh(DEVICES[:n], ('sp',))\n"
    )
    found = findings_for(p, "stale-device-set")
    assert [f.line for f in found] == [5]
    assert "DEVICES" in found[0].message


def test_implicit_upcast_triggers_in_hot_path_dirs(tmp_path):
    """ISSUE 7 satellite: a contraction over bf16/int8-cast operands with
    no explicit preferred_element_type, in a hot-path module, is flagged —
    inline casts and name-bound casts alike."""
    d = tmp_path / "ops"
    d.mkdir()
    p = d / "hot.py"
    p.write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def mix(x, w):\n"
        "    return jnp.dot(x.astype(jnp.bfloat16), w)\n"
        "def bound(x, w):\n"
        "    xb = x.astype(jnp.int8)\n"
        "    return lax.dot_general(xb, w, (((1,), (0,)), ((), ())))\n"
    )
    found = findings_for(p, "implicit-upcast")
    assert [f.line for f in found] == [4, 7]
    assert all("preferred_element_type" in f.message for f in found)


def test_implicit_upcast_explicit_accumulate_ok(tmp_path):
    """Stating the accumulation dtype (the precision-subsystem contract)
    silences the rule; fp32-only contractions are never judged."""
    d = tmp_path / "precision"
    d.mkdir()
    p = d / "quantize.py"
    p.write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def stated(x, w):\n"
        "    return jnp.dot(x.astype(jnp.bfloat16), w,\n"
        "                   preferred_element_type=jnp.float32)\n"
        "def fp32_only(x, w):\n"
        "    return jnp.dot(x.astype(jnp.float32), w)\n"
        "def unknown_dtypes(x, w):\n"
        "    return jnp.dot(x, w)\n"
    )
    assert findings_for(p, "implicit-upcast") == []


def test_implicit_upcast_scoping_and_noqa(tmp_path):
    """Out of the hot-path dirs (ops/models/parallel/precision) the rule is
    silent; in scope, # noqa documents a deliberate inference."""
    src = (
        "import jax.numpy as jnp\n"
        "def mix(x, w):\n"
        "    return jnp.dot(x.astype(jnp.bfloat16), w)\n"
    )
    cold = tmp_path / "analysis.py"
    cold.write_text(src)
    assert findings_for(cold, "implicit-upcast") == []
    d = tmp_path / "models"
    d.mkdir()
    hot = d / "net.py"
    hot.write_text(src.replace(", w)", ", w)  # noqa: implicit-upcast"))
    assert findings_for(hot, "implicit-upcast") == []


def test_implicit_upcast_repo_hot_paths_clean():
    """The shipped mixed-precision code states its accumulation dtype: the
    rule's own scope stays 0-findings (the baseline stays empty)."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        ImplicitUpcastRule,
    )

    rule = ImplicitUpcastRule()
    assert rule.applies(
        Path("cuda_mpi_gpu_cluster_programming_tpu/precision/quantize.py")
    )
    assert not rule.applies(Path("cuda_mpi_gpu_cluster_programming_tpu/run.py"))
    pkg = ROOT / "cuda_mpi_gpu_cluster_programming_tpu"
    files = [
        f
        for sub in ("ops", "models", "parallel", "precision")
        for f in sorted((pkg / sub).glob("*.py"))
    ]
    assert files
    assert [f for f in files if findings_for(f, "implicit-upcast")] == []


# ---------------------------------------------------------------------------
# engine features


def test_noqa_resolves_over_statement_span(tmp_path):
    """The historical false-positive: a multi-line construct whose finding
    reports one line while the # noqa sits on another line of the same
    statement. Both directions must suppress."""
    p = tmp_path / "span.py"
    p.write_text(
        "def f(\n"
        "    a,\n"
        "    b=[],\n"
        "):  # noqa: mutable-default\n"
        "    return a, b\n"
    )
    assert findings_for(p, "mutable-default") == []
    # raw-subprocess on a multi-line call, noqa on the closing line.
    q = tmp_path / "scripts" / "multi.py"
    q.parent.mkdir()
    q.write_text(
        "import subprocess\n"
        "subprocess.run(\n"
        "    ['true'],\n"
        ")  # noqa: raw-subprocess\n"
    )
    assert findings_for(q, "raw-subprocess") == []
    # Control: without the annotation both fire.
    r = tmp_path / "scripts" / "bare.py"
    r.write_text("import subprocess\nsubprocess.run(\n    ['true'],\n)\n")
    assert len(findings_for(r, "raw-subprocess")) == 1


def test_noqa_file_pragma(tmp_path):
    body = (
        "import jax\n"
        "def draws():\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    a = jax.random.normal(key, (4,))\n"
        "    b = jax.random.normal(key, (4,))\n"
        "    return a, b\n"
    )
    p = tmp_path / "gen.py"
    p.write_text("# generated file\n# noqa-file: key-reuse\n" + body)
    assert findings_for(p, "key-reuse") == []
    # The pragma only counts in the first 5 lines.
    q = tmp_path / "late.py"
    q.write_text(body + "# noqa-file: key-reuse\n")
    assert len(findings_for(q, "key-reuse")) == 1
    # Bare pragma suppresses everything.
    r = tmp_path / "all.py"
    r.write_text("# noqa-file\n" + body + "import os\n")
    assert findings_for(r) == []


def test_baseline_counts_allow_old_fail_new(tmp_path):
    p = tmp_path / "keyreuse.py"
    p.write_text(_KEY_REUSE[1])
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(baseline_payload(findings_for(p), ROOT)))
    rc, _ = run_engine([p], baseline_path=bp)
    assert rc == 0
    # A SECOND reuse in the same file exceeds the grandfathered count: the
    # extra finding (and only it) fails the run.
    p.write_text(
        _KEY_REUSE[1].replace(
            "    return a, b\n",
            "    c = jax.random.normal(key, (4,))\n    return a, b, c\n",
        )
    )
    rc, out = run_engine([p], baseline_path=bp)
    assert rc == 1
    assert out.count("[key-reuse]") == 1
    assert "1 baselined" in out


def test_baseline_update_roundtrip(tmp_path):
    p = tmp_path / "keyreuse.py"
    p.write_text(_KEY_REUSE[1])
    bp = tmp_path / "baseline.json"
    rc, _ = run_engine([p], baseline_path=bp, update=True)
    assert rc == 0 and bp.exists()
    data = json.loads(bp.read_text())
    assert data["version"] == 1
    assert list(data["entries"].values()) == [{"key-reuse": 1}]
    rc, _ = run_engine([p], baseline_path=bp)
    assert rc == 0


def test_split_by_baseline_orders_by_line(tmp_path):
    p = tmp_path / "keyreuse.py"
    p.write_text(
        _KEY_REUSE[1].replace(
            "    return a, b\n",
            "    c = jax.random.normal(key, (4,))\n    return a, b, c\n",
        )
    )
    found = findings_for(p, "key-reuse")
    assert len(found) == 2
    baseline = {engine.baseline_key(p, ROOT): {"key-reuse": 1}}
    new, old = split_by_baseline(found, baseline, ROOT)
    assert [f.line for f in old] == [5]  # earliest finding grandfathered
    assert [f.line for f in new] == [6]


def test_json_format(tmp_path):
    p = tmp_path / "keyreuse.py"
    p.write_text(_KEY_REUSE[1])
    rc, out = run_engine([p], fmt="json")
    assert rc == 1
    data = json.loads(out)
    assert data["files"] == 1
    assert data["grandfathered"] == []
    (f,) = data["new"]
    assert f["code"] == "key-reuse" and f["line"] == 5
    assert f["severity"] == "error"


def test_syntax_error_single_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    got = findings_for(p)
    assert len(got) == 1 and got[0].code == "syntax"


def test_cli_module_entry_on_fixture(tmp_path):
    p = tmp_path / "keyreuse.py"
    p.write_text(_KEY_REUSE[1])
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.staticcheck",
            "--no-baseline", str(p),
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=60,
    )
    assert proc.returncode == 1
    assert "[key-reuse]" in proc.stdout


def test_cli_list_rules_has_all_new_codes():
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.staticcheck",
            "--list-rules",
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=60,
    )
    assert proc.returncode == 0
    for code in (
        "collective-axis", "unreduced-contraction", "host-sync-in-hot-loop",
        "key-reuse", "jit-in-loop", "check-vma-disabled", "implicit-upcast",
        "stale-device-set", "span-write-in-timed-region",
        "blocking-socket-call-in-timed-region",
        "raw-subprocess", "atomic-write", "variant-env", "deprecated",
    ):
        assert code in proc.stdout, code


# ---------------------------------------------------------------------------
# span-write-in-timed-region (ISSUE 9) + observability host-sync scope


_SPAN_WRITE_SRC = (
    "import time\n"
    "def loop(tracer, reg, batches, fwd):\n"
    "    for b in batches:\n"
    "        t0 = time.perf_counter()\n"
    "        out = fwd(b)\n"
    "        ms = (time.perf_counter() - t0) * 1e3\n"
    "        reg.histogram('batch_ms').observe(ms)\n"  # line 7: flagged
    "    return out\n"
)


def test_span_write_in_timed_region_triggers(tmp_path):
    """A metric observation inside a timed dispatch loop is flagged in a
    hot-loop-scoped file (here: a serving-named fixture)."""
    p = tmp_path / "server.py"
    p.write_text(_SPAN_WRITE_SRC)
    found = findings_for(p, "span-write-in-timed-region")
    assert len(found) == 1 and found[0].line == 7
    assert "off_timed_path" in found[0].message


def test_span_write_covers_emit_and_span_ctx(tmp_path):
    p = tmp_path / "loadgen.py"
    p.write_text(
        "import time\n"
        "from cuda_mpi_gpu_cluster_programming_tpu.observability.trace import span\n"
        "def loop(tracer, xs):\n"
        "    while xs:\n"
        "        t0 = time.monotonic()\n"
        "        with span('dispatch'):\n"      # line 6: flagged (ctx form)
        "            xs.pop()\n"
        "        tracer.emit('x', t0, time.monotonic())\n"  # line 8: flagged
    )
    found = findings_for(p, "span-write-in-timed-region")
    assert sorted(f.line for f in found) == [6, 8]


def test_span_write_untimed_loop_and_off_timed_path_exempt(tmp_path):
    """Only TIMED regions are in scope, and @off_timed_path persistence
    helpers are exempt by contract — the serving completion path."""
    p = tmp_path / "server.py"
    p.write_text(
        "import time\n"
        "def off_timed_path(fn):\n"
        "    return fn\n"
        "def drain(reg, batches):\n"
        "    for b in batches:\n"          # no clock read: not a timed region
        "        reg.counter('ok').inc()\n"
        "@off_timed_path\n"
        "def complete(tracer, reg, batches):\n"
        "    for b in batches:\n"
        "        t0 = time.perf_counter()\n"
        "        reg.histogram('ms').observe(time.perf_counter() - t0)\n"
        "        tracer.emit('dispatch', t0, time.perf_counter())\n"
    )
    assert findings_for(p, "span-write-in-timed-region") == []


def test_span_write_noqa(tmp_path):
    p = tmp_path / "server.py"
    src = _SPAN_WRITE_SRC.replace(
        ".observe(ms)\n", ".observe(ms)  # noqa: span-write-in-timed-region\n"
    )
    p.write_text(src)
    assert findings_for(p, "span-write-in-timed-region") == []


def test_observability_scope_and_shipped_modules_clean():
    """ISSUE 9 satellite: observability/ joins the host-sync scope (an
    instrumentation layer that syncs inside the loops it instruments
    corrupts what it reports), the new span-write rule covers it, and the
    shipped modules are clean under both rules."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        HostSyncInHotLoopRule,
        SpanWriteInTimedRegionRule,
    )

    obs = "cuda_mpi_gpu_cluster_programming_tpu/observability"
    for rule in (HostSyncInHotLoopRule(), SpanWriteInTimedRegionRule()):
        assert rule.applies(Path(f"{obs}/trace.py"))
        assert rule.applies(Path("cuda_mpi_gpu_cluster_programming_tpu/run.py"))
        assert not rule.applies(
            Path("cuda_mpi_gpu_cluster_programming_tpu/analysis.py")
        )
    # ISSUE 12/13/15: the directory scope grows with the subsystem — the
    # replay pacing loop (a timed loop re-driving a recorded arrival
    # schedule), the gate, the roofline/specs modules, and the fleet
    # health analyzer are covered the moment they exist, and ship clean.
    for mod in (
        "trace.py", "metrics.py", "stages.py", "export.py",
        "replay.py", "gate.py", "roofline.py", "specs.py", "health.py",
    ):
        for rule in (HostSyncInHotLoopRule(), SpanWriteInTimedRegionRule()):
            assert rule.applies(Path(f"{obs}/{mod}"))
        assert findings_for(ROOT / obs / mod, "host-sync-in-hot-loop") == []
        assert findings_for(ROOT / obs / mod, "span-write-in-timed-region") == []
    # the wired hot paths stay clean too (persistence lives in
    # @off_timed_path helpers by construction)
    for rel in (
        "cuda_mpi_gpu_cluster_programming_tpu/serving/server.py",
        "cuda_mpi_gpu_cluster_programming_tpu/resilience/supervisor.py",
        "bench.py",
    ):
        assert findings_for(ROOT / rel, "span-write-in-timed-region") == []


# ---------------------------------------------------------------------------
# blocking-socket-call-in-timed-region (ISSUE 11) + frontend hot-loop scope


_SOCKET_SRC = (
    "import time\n"
    "def pump(sock, batches):\n"
    "    for b in batches:\n"
    "        t0 = time.perf_counter()\n"
    "        data = sock.recv(4096)\n"  # line 5: flagged
    "        ms = (time.perf_counter() - t0) * 1e3\n"
    "    return ms\n"
)


def test_blocking_socket_in_timed_region_triggers(tmp_path):
    """A socket recv inside a timed dispatch loop is flagged in a
    hot-loop-scoped file (here: a frontend-named fixture)."""
    p = tmp_path / "frontend.py"
    p.write_text(_SOCKET_SRC)
    found = findings_for(p, "blocking-socket-call-in-timed-region")
    assert len(found) == 1 and found[0].line == 5
    assert "off_timed_path" in found[0].message


def test_blocking_socket_covers_client_calls(tmp_path):
    p = tmp_path / "loadgen.py"
    p.write_text(
        "import time\n"
        "from urllib.request import urlopen\n"
        "def fleet(conn, urls):\n"
        "    while urls:\n"
        "        t0 = time.monotonic()\n"
        "        conn.connect()\n"                 # line 6: flagged
        "        resp = conn.getresponse()\n"      # line 7: flagged
        "        urlopen(urls.pop())\n"            # line 8: flagged
        "        dt = time.monotonic() - t0\n"
    )
    found = findings_for(p, "blocking-socket-call-in-timed-region")
    assert sorted(f.line for f in found) == [6, 7, 8]


def test_blocking_socket_untimed_loop_off_timed_path_and_noqa(tmp_path):
    """Only TIMED regions are in scope; @off_timed_path transport helpers
    are exempt by contract; a deliberate latency-measuring client loop
    carries a reviewed # noqa."""
    p = tmp_path / "frontend.py"
    p.write_text(
        "import time\n"
        "def off_timed_path(fn):\n"
        "    return fn\n"
        "def drain(sock, batches):\n"
        "    for b in batches:\n"          # no clock read: not a timed region
        "        sock.sendall(b)\n"
        "@off_timed_path\n"
        "def transport(sock, batches):\n"
        "    for b in batches:\n"
        "        t0 = time.monotonic()\n"
        "        sock.sendall(b)\n"
        "        data = sock.recv(4096)\n"
        "        dt = time.monotonic() - t0\n"
    )
    assert findings_for(p, "blocking-socket-call-in-timed-region") == []
    q = tmp_path / "server.py"
    q.write_text(
        _SOCKET_SRC.replace(
            ".recv(4096)\n",
            ".recv(4096)  # noqa: blocking-socket-call-in-timed-region\n",
        )
    )
    assert findings_for(q, "blocking-socket-call-in-timed-region") == []


def test_blocking_socket_scope_and_shipped_serving_clean():
    """ISSUE 11 satellite: the serving front end + traffic/SLO layers join
    the hot-loop scope, and the shipped modules are clean under both the
    host-sync and blocking-socket rules (the client fleet's one
    deliberate socket wait carries its reviewed # noqa)."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        BlockingSocketInTimedRegionRule,
        HostSyncInHotLoopRule,
    )

    serving = "cuda_mpi_gpu_cluster_programming_tpu/serving"
    for rule in (HostSyncInHotLoopRule(), BlockingSocketInTimedRegionRule()):
        assert rule.applies(Path(f"{serving}/frontend.py"))
        assert rule.applies(Path(f"{serving}/traffic.py"))
        assert rule.applies(Path(f"{serving}/slo.py"))
        assert not rule.applies(
            Path("cuda_mpi_gpu_cluster_programming_tpu/analysis.py")
        )
    for mod in ("frontend.py", "traffic.py", "slo.py", "server.py", "loadgen.py"):
        assert findings_for(ROOT / serving / mod, "host-sync-in-hot-loop") == []
        assert findings_for(
            ROOT / serving / mod, "blocking-socket-call-in-timed-region"
        ) == []


def test_router_tier_in_hot_loop_scope_and_clean():
    """ISSUE 16 satellite: the fleet router tier (serving/router.py +
    serving/fleet.py) joins the hot-loop scope — its probe/forward waits
    are timed regions — and ships clean: the deliberate socket waits
    (the probe IS the health measurement; the hop wait IS the redirect
    budget) carry their reviewed # noqa."""
    from cuda_mpi_gpu_cluster_programming_tpu.staticcheck.rules_jax import (
        BlockingSocketInTimedRegionRule,
        HostSyncInHotLoopRule,
    )

    serving = "cuda_mpi_gpu_cluster_programming_tpu/serving"
    for rule in (HostSyncInHotLoopRule(), BlockingSocketInTimedRegionRule()):
        assert rule.applies(Path(f"{serving}/router.py"))
        assert rule.applies(Path(f"{serving}/fleet.py"))
    for mod in ("router.py", "fleet.py"):
        assert findings_for(ROOT / serving / mod, "host-sync-in-hot-loop") == []
        assert findings_for(
            ROOT / serving / mod, "blocking-socket-call-in-timed-region"
        ) == []

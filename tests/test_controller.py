"""Autopilot closed-loop controller tests (ISSUE 18, docs/SERVING.md
"Autopilot").

The contract under test: the controller folds the PR 15 error-budget
math incrementally over the live outcome stream, walks a fixed pressure
ladder (shed bulk -> shed batch -> narrow buckets -> dtype downshift /
supervised degrade) only when the protected class burns or the queue
wait nears the saturation knee, journals EVERY transition as a
``controller_action`` record carrying its triggering evidence, and is
hysteresis-bounded (cooldown between actions, min-dwell before
de-escalating). No silent actuation: the dtype rung only fires after a
journaled ToleranceGate pass, refusals are journaled and the rung is
blocked, every action is reversible and every reversal journaled.

The acceptance halves: a saturating drill (bulk shed FIRST, interactive
never tightened, accounting closed) and the ``replay --controller
on|off`` A/B over one recorded saturating trace (books closed both
ways, actions journaled with evidence on the on side, protected-class
burn strictly lower with the controller on, calm trace => zero
actions) — the tier-1 gate ``BENCH_MODE=control`` re-runs from
``scripts/on_heal.sh``.
"""

import dataclasses
import types
from pathlib import Path

import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import BLOCKS12
from cuda_mpi_gpu_cluster_programming_tpu.observability.health import (
    health_from_journal,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability.replay import (
    ReplayKnobs,
    load_recorded_run,
    replay_recorded,
)
from cuda_mpi_gpu_cluster_programming_tpu.resilience import chaos
from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal
from cuda_mpi_gpu_cluster_programming_tpu.serving.controller import (
    AutopilotController,
    ControllerConfig,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.loadgen import (
    run_shaped_load,
    saturating_rate,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.server import (
    InferenceServer,
    ServeConfig,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.traffic import (
    default_class_mix,
    slo_policy,
)

ROOT = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)

# Unit-drill knobs: no eval throttle (every evaluate() call decides),
# explicit cooldown/dwell driven through evaluate(now=...) injection,
# a small trusted-burn window, and ONLY the admission rungs enabled so
# the pure-policy drills never touch a compiled forward.
UNIT = ControllerConfig(
    eval_s=0.0,
    window=16,
    min_completed=5,
    cooldown_s=1.0,
    min_dwell_s=2.0,
    enable_buckets=False,
    enable_dtype=False,
    enable_degrade=False,
)

# CI-cadence controller for the live drills: production thresholds and
# ladder, dwell/cooldown shrunk to sub-second load windows.
SNAPPY = ControllerConfig(
    eval_s=0.05, cooldown_s=0.2, min_dwell_s=0.3, min_completed=10
)


@pytest.fixture(autouse=True)
def _chaos_off(monkeypatch):
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _server(tmp_path, name, *, controller, slo=True, **kw):
    mix = list(default_class_mix([1, 2, 4]))
    scfg = ServeConfig(
        config=kw.pop("config", "v1_jit"),
        max_batch=kw.pop("max_batch", 4),
        journal_path=str(tmp_path / name),
        model_cfg=CFG,
        default_deadline_s=30.0,
        slo=slo_policy(mix) if slo else None,
        controller=controller,
        **kw,
    )
    return InferenceServer(scfg), mix


def _actions(journal_path):
    return [
        r for r in Journal.load(journal_path)
        if r["kind"] == "controller_action"
    ]


def _feed(ctl, cls, n, late):
    slo_ms = ctl.base_slo.class_for(cls).slo_ms
    for _ in range(n):
        ctl.note_ok(cls, slo_ms * (2.0 if late else 0.1))


# --------------------------------------------------------- unit drills ---


def test_inert_without_slo_policy(tmp_path):
    """No SLO policy => no burn, no knee: the controller never journals
    and never actuates, by design (docs/SERVING.md 'Autopilot')."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT, slo=False)
    ctl = srv.controller
    assert ctl is not None and ctl.base_slo is None
    ctl.note_shed("interactive")
    assert ctl.evaluate(now=100.0) is None
    assert ctl.mode == "steady" and _actions(srv.cfg.journal_path) == []


def test_no_action_below_threshold(tmp_path):
    """A healthy signal fold (burn 0, empty queue) never actuates — the
    calm-path half of the acceptance contract."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    _feed(ctl, "interactive", 16, late=False)
    for t in (100.0, 101.0, 102.0):
        assert ctl.evaluate(now=t) is None
    assert ctl.mode == "steady" and _actions(srv.cfg.journal_path) == []


def test_untrusted_window_does_not_actuate(tmp_path):
    """Fewer than min_completed outcomes => burn is None (noise must not
    actuate), even when every one of them violated."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    _feed(ctl, "interactive", UNIT.min_completed - 1, late=True)
    assert ctl.burn("interactive") is None
    assert ctl.evaluate(now=100.0) is None
    assert _actions(srv.cfg.journal_path) == []


def test_escalation_sheds_bulk_first_with_journaled_evidence(tmp_path):
    """Protected-class burn >= burn_high escalates rung 1: bulk admission
    tightens to the protected class's SLO budget on the queue's pop-time
    path — base policy untouched — and the journaled record carries the
    full triggering evidence."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    _feed(ctl, "interactive", 8, late=True)
    rec = ctl.evaluate(now=100.0)
    assert rec is not None
    assert rec["action"] == "tighten_admission" and rec["target"] == "bulk"
    assert rec["actuated"] is True and rec["reversal"] is False
    assert rec["level"] == 1 and ctl.mode == "degraded"
    # the live policy moved; the base (product) policy did not. The
    # tightened cut lands BELOW the protected budget (tighten_factor) —
    # at an equal cut the shared queue wait sheds everyone alike and
    # the protected class gains nothing.
    protected_slo = ctl.base_slo.class_for("interactive").slo_ms
    tightened_cut = protected_slo * UNIT.tighten_factor
    assert srv.queue.slo.class_for("bulk").shed_cut_ms == tightened_cut
    assert ctl.base_slo.class_for("bulk").shed_cut_ms == 0.0
    assert srv.queue.slo.class_for("interactive").slo_ms == protected_slo
    # evidence: the signals, the thresholds they crossed, the hysteresis
    ev = rec["evidence"]
    assert ev["burn"]["interactive"] >= ev["burn_high"]
    for k in ("oldest_wait_ms", "depth", "knee_frac", "cooldown_s",
              "min_dwell_s", "completed"):
        assert k in ev
    recs = _actions(srv.cfg.journal_path)
    assert len(recs) == 1 and recs[0]["action"] == "tighten_admission"
    assert recs[0]["evidence"]["burn"]["interactive"] == ev["burn"]["interactive"]


def test_cooldown_blocks_flapping(tmp_path):
    """A still-hot signal inside cooldown_s does NOT stack a second rung;
    after the cooldown it does (batch — the shed order is bulk first)."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    _feed(ctl, "interactive", 8, late=True)
    assert ctl.evaluate(now=100.0)["target"] == "bulk"
    assert ctl.evaluate(now=100.5) is None  # cooling
    rec = ctl.evaluate(now=101.2)
    assert rec["action"] == "tighten_admission" and rec["target"] == "batch"
    assert rec["evidence"]["since_last_action_s"] == pytest.approx(1.2)
    assert ctl.level == 2


def test_min_dwell_blocks_immediate_deescalate_and_reversal_journaled(
    tmp_path,
):
    """Recovery reverses LIFO — but only after min_dwell_s at the level,
    and the reversal is journaled like any action."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    base = srv.queue.slo
    _feed(ctl, "interactive", 8, late=True)
    assert ctl.evaluate(now=100.0) is not None
    _feed(ctl, "interactive", 16, late=False)  # flush the window clean
    assert ctl.burn("interactive") == 0.0
    assert ctl.evaluate(now=101.2) is None  # cooled, but not dwelled
    rec = ctl.evaluate(now=102.5)
    assert rec["action"] == "relax_admission" and rec["reversal"] is True
    assert rec["actuated"] is True and rec["target"] == "bulk"
    assert rec["evidence"]["dwell_s"] == pytest.approx(2.5)
    assert ctl.mode == "steady" and ctl.level == 0
    assert srv.queue.slo is base  # the exact pre-action policy object
    kinds = [(r["action"], r["reversal"]) for r in _actions(srv.cfg.journal_path)]
    assert kinds == [("tighten_admission", False), ("relax_admission", True)]


def test_knee_trigger_without_burn(tmp_path):
    """The queue-wait knee escalates BEFORE any SLO is blown — the
    early-warning half of the trigger (oldest_wait vs the tightest shed
    cut), independent of the burn windows."""
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    ctl = srv.controller
    knee = min(
        c.shed_cut_ms for c in ctl.base_slo.classes.values() if c.shed_cut_ms
    )
    stats = srv.queue.stats()
    srv.queue.stats = lambda: dataclasses.replace(
        stats, depth=9, oldest_wait_ms=0.9 * knee
    )
    rec = ctl.evaluate(now=100.0)
    assert rec is not None and rec["action"] == "tighten_admission"
    assert rec["evidence"]["oldest_wait_ms"] == pytest.approx(0.9 * knee)
    assert rec["evidence"]["knee_ms"] == knee


def test_gate_refused_downshift_is_journaled_and_blocked(tmp_path, monkeypatch):
    """No silent dtype adoption: a failed ToleranceGate screen journals
    ``downshift_refused`` (actuated=False, cause from the gate), the
    compute is untouched, and the rung is blocked — never retried
    blind."""
    ctl_cfg = dataclasses.replace(
        UNIT, enable_admission=False, enable_dtype=True
    )
    srv, _ = _server(tmp_path, "j.jsonl", controller=ctl_cfg, compute="bf16")
    ctl = srv.controller
    monkeypatch.setattr(
        AutopilotController,
        "_screen_dtype",
        lambda self, compute: types.SimpleNamespace(
            passed=False, margin=float("-inf"), reason=lambda: "stub fail"
        ),
    )
    _feed(ctl, "interactive", 8, late=True)
    # a refusal is journaled but never RETURNED: evaluate only returns
    # actuations, and the ladder had nothing else to try
    assert ctl.evaluate(now=100.0) is None
    recs = _actions(srv.cfg.journal_path)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["action"] == "downshift_refused" and rec["actuated"] is False
    assert "gate refused" in rec["cause"]
    assert srv.current_compute == "bf16" and ctl.mode == "steady"
    # blocked: the still-hot signal finds no rung left — exactly one
    # refusal in the journal, no second attempt
    assert ctl.evaluate(now=105.0) is None
    assert [r["action"] for r in _actions(srv.cfg.journal_path)] == [
        "downshift_refused"
    ]


def test_real_gate_downshift_and_upshift_roundtrip(tmp_path):
    """The dtype rung end to end on a real (unstarted) server: a REAL
    ToleranceGate screen passes (gate_pass journaled under the
    controller's key), the forward rebuilds at int8w and re-warms, and
    the recovery reversal restores the configured compute."""
    ctl_cfg = dataclasses.replace(
        UNIT, enable_admission=False, enable_dtype=True
    )
    srv, _ = _server(tmp_path, "j.jsonl", controller=ctl_cfg)
    srv._ensure_built()
    srv.warmup()
    ctl = srv.controller
    _feed(ctl, "interactive", 8, late=True)
    rec = ctl.evaluate(now=100.0)
    assert rec is not None
    assert rec["action"] == "downshift_dtype" and rec["actuated"] is True
    assert srv.current_compute == "int8w"
    assert srv.cfg.compute == "fp32"  # config untouched: it's an override
    _feed(ctl, "interactive", 16, late=False)
    rev = ctl.evaluate(now=103.0)
    assert rev["action"] == "upshift_dtype" and rev["reversal"] is True
    assert srv.current_compute == "fp32"
    kinds = [r["kind"] for r in Journal.load(srv.cfg.journal_path)]
    assert "gate_pass" in kinds  # the screen's own journal trail
    rewarms = [
        r for r in Journal.load(srv.cfg.journal_path)
        if r["kind"] == "serve_rewarm"
    ]
    assert len(rewarms) == 2  # downshift + upshift each re-warmed


def test_supervised_degrade_and_promote_rung(tmp_path):
    """On a supervised server the capacity rung degrades through the
    Supervisor ladder as a journaled capacity DECISION (cause
    ``requested:``), and the reversal is the sentinel-verified explicit
    promotion."""
    ctl_cfg = dataclasses.replace(
        UNIT, enable_admission=False, enable_degrade=True
    )
    srv, _ = _server(
        tmp_path, "j.jsonl", controller=ctl_cfg,
        config="v2.2_sharded", n_shards=2, supervise=True,
    )
    srv._ensure_built()
    ctl = srv.controller
    entry0 = srv.sup.entry.key
    _feed(ctl, "interactive", 8, late=True)
    rec = ctl.evaluate(now=100.0)
    assert rec is not None
    assert rec["action"] == "degrade_capacity" and rec["actuated"] is True
    assert rec["frm"] == entry0 and rec["to"] == srv.sup.entry.key
    assert srv.sup.entry.key != entry0
    degrades = [
        r for r in Journal.load(srv.cfg.journal_path)
        if r["kind"] == "sup_degrade"
    ]
    assert degrades and degrades[-1]["cause"].startswith("requested:")
    _feed(ctl, "interactive", 16, late=False)
    rev = ctl.evaluate(now=103.0)
    assert rev["action"] == "promote_capacity" and rev["reversal"] is True
    assert srv.sup.entry.key == entry0


def test_bucket_narrow_and_widen_rewarm(tmp_path):
    """The bucket rung drops the widest bucket and the reversal re-warms
    it before it can compile on the request path."""
    ctl_cfg = dataclasses.replace(
        UNIT, enable_admission=False, enable_buckets=True
    )
    srv, _ = _server(tmp_path, "j.jsonl", controller=ctl_cfg)
    srv._ensure_built()
    srv.warmup()
    ctl = srv.controller
    assert srv.buckets == (1, 2, 4)
    _feed(ctl, "interactive", 8, late=True)
    rec = ctl.evaluate(now=100.0)
    assert rec["action"] == "narrow_buckets" and srv.buckets == (1, 2)
    _feed(ctl, "interactive", 16, late=False)
    rev = ctl.evaluate(now=103.0)
    assert rev["action"] == "widen_buckets" and srv.buckets == (1, 2, 4)
    assert 4 in srv._warmed  # re-warmed on widen, not lazily


def test_controller_config_roundtrip_and_state_obj(tmp_path):
    """ControllerConfig round-trips through to_obj/from_obj (the
    serve_config record replay rebuilds from; unknown keys ignored), and
    state_obj carries what /healthz exposes."""
    cfg = ControllerConfig(burn_high=2.0, shed_order=("batch",))
    obj = cfg.to_obj()
    assert ControllerConfig.from_obj({**obj, "novel_knob": 1}) == cfg
    srv, _ = _server(tmp_path, "j.jsonl", controller=UNIT)
    srv._ensure_built()  # writes the serve_config header
    ctl = srv.controller
    _feed(ctl, "interactive", 8, late=True)
    ctl.evaluate(now=100.0)
    st = ctl.state_obj(now=101.0)
    assert st["mode"] == "degraded" and st["level"] == 1
    assert st["overrides"] == [
        {"action": "tighten_admission", "target": "bulk"}
    ]
    assert st["last_action"]["action"] == "tighten_admission"
    assert st["last_action"]["age_s"] == pytest.approx(1.0)
    assert st["actions"] == {"tighten_admission": 1}
    # the serve_config header carries the controller knobs for replay
    hdr = next(
        r for r in Journal.load(srv.cfg.journal_path)
        if r["kind"] == "serve_config"
    )
    assert hdr["controller"]["burn_high"] == UNIT.burn_high


# ------------------------------------------------- acceptance: live drill ---


@pytest.fixture(scope="module")
def sat_rate(tmp_path_factory):
    """The saturating request rate for the live drill and the A/B
    recording, derived from a short SATURATED, SLO-free capacity probe
    (loadgen.saturating_rate). A fixed rate flakes on hosts whose speed
    varies 3x: too low and nothing burns (vacuous drill), too high and
    both A/B sides peg at the burn cap — the usable regime
    oversubscribes ~1.5x while the protected class alone still fits."""
    jp = tmp_path_factory.mktemp("autopilot_probe") / "probe.jsonl"
    mix = list(default_class_mix([1, 2, 4]))
    scfg = ServeConfig(
        config="v1_jit",
        max_batch=4,
        journal_path=str(jp),
        model_cfg=CFG,
        default_deadline_s=30.0,
    )
    srv = InferenceServer(scfg)
    srv.start()
    try:
        run_shaped_load(
            srv, shape="steady", rate_rps=2000.0, duration_s=0.3,
            classes=mix, seed=0,
        )
    finally:
        srv.stop()
    return saturating_rate(str(jp), mix)


@pytest.fixture(scope="module")
def saturating_drill(tmp_path_factory, sat_rate):
    """One saturating controller-ON run: rate past the probed 63x63 CPU
    capacity with SLOs scaled tight, so the ladder demonstrably walks."""
    jp = tmp_path_factory.mktemp("autopilot") / "drill.jsonl"
    mix = list(default_class_mix([1, 2, 4]))
    scfg = ServeConfig(
        config="v1_jit",
        max_batch=4,
        journal_path=str(jp),
        model_cfg=CFG,
        default_deadline_s=30.0,
        slo=slo_policy(mix).scaled(0.15),
        controller=SNAPPY,
    )
    srv = InferenceServer(scfg)
    srv.start()
    try:
        report = run_shaped_load(
            srv, shape="steady", rate_rps=sat_rate, duration_s=1.2,
            classes=mix, seed=0,
        )
    finally:
        srv.stop()
    return jp, report, srv.controller.state_obj()


def test_saturating_drill_bulk_shed_first_interactive_preserved(
    saturating_drill,
):
    """The live acceptance drill: the controller acts (journaled, with
    evidence), bulk is tightened before anything else, the protected
    class's admission is NEVER tightened, and per-class accounting
    closes despite the actuation."""
    jp, report, state = saturating_drill
    recs = _actions(jp)
    actuated = [r for r in recs if r["actuated"]]
    assert actuated, "saturating drill journaled no controller actions"
    assert actuated[0]["action"] == "tighten_admission"
    assert actuated[0]["target"] == "bulk"
    assert all(
        r["target"] != "interactive"
        for r in recs
        if r["action"] == "tighten_admission"
    )
    for r in recs:
        ev = r["evidence"]
        assert "burn" in ev and "oldest_wait_ms" in ev and "depth" in ev
    assert report.closed  # every class: offered == ok+shed+failed+rejected
    assert state["actions"] and sum(state["actions"].values()) == len(recs)


def test_health_report_counts_controller_actions(saturating_drill):
    """ISSUE 18 satellite: the fleet-health fold counts controller
    actions and splits protected-class burn at the first actuation (the
    did-it-help attribution); --fail-on-budget-burn semantics ride the
    same classes as before."""
    jp, _, _ = saturating_drill
    rep = health_from_journal(jp)
    ctl = rep.controller
    assert ctl["total"] == len(_actions(jp)) and ctl["actions"]
    assert "burn_after" in ctl
    assert "controller" in rep.to_obj()
    assert any("Autopilot" in ln for ln in rep.render().splitlines())


def test_health_report_without_controller_records_unchanged(tmp_path):
    """Old-journal pin: a journal with no controller_action records folds
    into a HealthReport whose to_obj has NO controller key — pre-ISSUE-18
    tooling sees an unchanged schema."""
    jp = tmp_path / "old.jsonl"
    j = Journal(jp)
    j.append("serve_config", key="config", config="v1_jit", n_shards=1,
             max_batch=4, buckets=[1, 2, 4])
    j.append("serve_batch", key="batch:0", bucket=2, batch_ms=3.0,
             req_lat_ms={"r1": 4.0})
    rep = health_from_journal(jp)
    assert rep.controller == {} and "controller" not in rep.to_obj()


# -------------------------------------------------- acceptance: replay A/B ---


@pytest.fixture(scope="module")
def recorded_saturating(tmp_path_factory, sat_rate):
    """A controller-OFF saturating recording — the trace both replay
    sides re-drive."""
    jp = tmp_path_factory.mktemp("autopilot_ab") / "recorded.jsonl"
    mix = list(default_class_mix([1, 2, 4]))
    scfg = ServeConfig(
        config="v1_jit",
        max_batch=4,
        journal_path=str(jp),
        model_cfg=CFG,
        default_deadline_s=30.0,
        slo=slo_policy(mix),
    )
    srv = InferenceServer(scfg)
    srv.start()
    try:
        run_shaped_load(
            srv, shape="steady", rate_rps=sat_rate, duration_s=1.2,
            classes=mix, seed=0,
        )
    finally:
        srv.stop()
    return jp


def test_replay_ab_controller_lowers_protected_burn(
    recorded_saturating, tmp_path
):
    """THE tier-1 A/B gate: one recorded saturating trace re-driven with
    ``--controller off`` then ``--controller on`` under equal SLO
    pressure. Both sides close per-class accounting and report no
    divergence; the on side journals actions with evidence and lands a
    STRICTLY lower protected-class error-budget burn."""
    recorded = load_recorded_run(recorded_saturating)
    reports = {}
    for mode in ("off", "on"):
        reports[mode] = replay_recorded(
            recorded,
            ReplayKnobs(
                controller=mode,
                controller_cfg=SNAPPY.to_obj(),
                slo_scale=0.15,
                journal_path=str(tmp_path / f"replay_{mode}.jsonl"),
            ),
        )
    off, on = reports["off"], reports["on"]
    for rep in (off, on):
        assert rep.accounting_closed and not rep.diverged
    assert not off.controller_active and on.controller_active
    on_actions = _actions(on.journal_path)
    assert on_actions and any(r["actuated"] for r in on_actions)
    assert all("evidence" in r for r in on_actions)
    assert _actions(off.journal_path) == []

    def burn(path):
        for c in health_from_journal(path).classes:
            if c.name == SNAPPY.protected_cls:
                return c.burn
        return None

    b_off, b_on = burn(off.journal_path), burn(on.journal_path)
    assert b_off is not None and b_on is not None
    assert b_on < b_off, f"controller on did not help: {b_on} vs {b_off}"
    # the on-side replay row carries the controller state for the bench row
    assert on.to_obj()["controller_state"]["actions"]


def test_calm_trace_replays_with_zero_actions(tmp_path):
    """Calm-path acceptance: a controller-ON recording far below capacity
    journals ZERO actions, and replaying it as-recorded (controller
    rebuilt from the serve_config header) also journals zero actions and
    never reports divergence."""
    jp = tmp_path / "calm.jsonl"
    mix = list(default_class_mix([1, 2, 4]))
    scfg = ServeConfig(
        config="v1_jit",
        max_batch=4,
        journal_path=str(jp),
        model_cfg=CFG,
        default_deadline_s=30.0,
        slo=slo_policy(mix),
        controller=SNAPPY,
    )
    srv = InferenceServer(scfg)
    srv.start()
    try:
        report = run_shaped_load(
            srv, shape="steady", rate_rps=10.0, duration_s=0.6,
            classes=mix, seed=0,
        )
    finally:
        srv.stop()
    assert report.closed and _actions(jp) == []
    assert srv.controller.state_obj()["mode"] == "steady"
    rep = replay_recorded(
        load_recorded_run(jp),
        ReplayKnobs(journal_path=str(tmp_path / "calm_replay.jsonl")),
    )
    assert rep.controller_active  # rebuilt from the recorded header
    assert rep.controller_state["mode"] == "steady"
    assert _actions(rep.journal_path) == []
    assert rep.accounting_closed and not rep.diverged

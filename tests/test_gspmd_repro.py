"""Pin the (non-)reproducibility of the GSPMD sp-axis conv-grad bug.

Round 1 documented a workaround in training.py: annotating the conv input's
H axis with the "sp" mesh axis under jit allegedly produced wrong conv
*weight* gradients, so sp-training was routed through the explicit
shard_map + ppermute halo path instead.

Round-2 investigation (scripts/gspmd_conv_grad_repro.py) could NOT reproduce
the bug on the CPU backend with jax==0.9.0 — not with a minimal conv, not
with the full Blocks 1-2 model at H=227, not with remat, not with a dp x sp
mesh. These tests pin that finding:

- test_gspmd_sp_annotation_grads_correct_on_cpu PASSES = GSPMD grads are
  correct on this backend/build. If it ever FAILS, the round-1 bug has
  appeared (e.g. after a JAX upgrade) and the shard_map routing in
  training.py is load-bearing for numerics, not just for design.
- The shard_map halo path remains the default for sp-training regardless:
  it is the framework's explicit-collectives design (the reference's MPI
  halo analogue), and the GSPMD behavior on the *axon TPU* backend — where
  the round-1 observation may have originated — is still unverified.

Run the paired script on a real TPU to settle the backend question:
    python scripts/gspmd_conv_grad_repro.py
"""

import importlib.util
import os



def _load_repro():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "gspmd_conv_grad_repro.py",
    )
    spec = importlib.util.spec_from_file_location("gspmd_conv_grad_repro", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gspmd_sp_annotation_grads_correct_on_cpu():
    # conftest.py already forces the 8-device virtual CPU mesh; do NOT call
    # the script's force_cpu() here (backend is already initialized).
    mod = _load_repro()
    wdiff, bdiff, ldiff = mod.grad_mismatch(n_shards=4)
    assert ldiff < 1e-4, f"forward loss diverged under sp annotation: {ldiff}"
    assert bdiff < 1e-4, f"bias grads diverged under sp annotation: {bdiff}"
    assert wdiff < 1e-3, (
        f"conv weight grads diverged under sp annotation (max|diff|={wdiff}): "
        "the round-1 GSPMD bug is BACK — the shard_map routing in "
        "training.py (x_spec) is now numerically load-bearing"
    )


def test_repro_script_exit_code_contract():
    """Drive the script as a CLI: rc 1 = bug absent, rc 0 = bug present."""
    import subprocess
    import sys

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "gspmd_conv_grad_repro.py",
    )
    proc = subprocess.run(
        [sys.executable, path], capture_output=True, text=True, timeout=300
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "bug NOT reproduced" in proc.stdout

"""FSDP / ZeRO parameter sharding: placement, math equivalence, training.

The module's claim is that placement IS the implementation — the same
jitted train step, with params device_put per fsdp_spec, runs data
parallelism whose parameter/optimizer memory scales 1/n. These tests pin
the spec rule, that placement actually engages for a real LM, that the
loss/step math is unchanged vs replicated DP, and that the updated params
keep their sharded placement (optimizer state inherits it through the
jit's propagation).
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    lm_loss,
    make_lm_train_step,
)
from cuda_mpi_gpu_cluster_programming_tpu.parallel.fsdp import (
    fsdp_spec,
    shard_params_fsdp,
    sharded_fraction,
)
from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh

CFG = TransformerConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=64)


def test_fsdp_spec_rule():
    # largest divisible dim is sharded
    assert fsdp_spec((128, 512), 8) == P(None, "dp")
    assert fsdp_spec((512, 128), 8) == P("dp", None)
    # largest-first preference when BOTH dims divide (index-order would
    # pick dim 0 here)
    assert fsdp_spec((8, 512), 4) == P(None, "dp")
    # fallback past an indivisible LARGER dim to a divisible smaller one
    assert fsdp_spec((10, 8), 4) == P(None, "dp")
    assert fsdp_spec((6, 512), 4) == P(None, "dp")
    assert fsdp_spec((8, 6), 4) == P("dp", None)
    # nothing divisible -> replicated; scalars -> replicated
    assert fsdp_spec((3, 5), 4) == P()
    assert fsdp_spec((), 4) == P()
    # custom axis name
    assert fsdp_spec((16,), 8, "fsdp") == P("fsdp")


def test_fsdp_placement_engages_for_lm():
    mesh = make_mesh(8, axis_name="dp")
    params = shard_params_fsdp(init_transformer(jax.random.PRNGKey(0), CFG), mesh)
    # Essentially all parameter bytes live sharded (embeddings + matmuls
    # dominate; only dp-indivisible stragglers may replicate).
    assert sharded_fraction(params) > 0.95


def test_fsdp_step_matches_replicated_dp():
    """One train step with FSDP-sharded params equals the replicated-DP
    step: same loss, same updated parameters (GSPMD placement must not
    change the math)."""
    mesh = make_mesh(8, axis_name="dp")
    key = jax.random.PRNGKey(1)
    params = init_transformer(key, CFG)
    tokens = jax.random.randint(key, (8, 33), 0, CFG.vocab)

    opt_init, step = make_lm_train_step(CFG, lr=1e-2)

    # replicated reference
    p_rep, _, loss_rep = step(params, opt_init(params), tokens)

    # fsdp: params sharded, batch sharded over the same axis
    fs = shard_params_fsdp(params, mesh)
    tok_dp = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    p_fs, opt_fs, loss_fs = step(fs, opt_init(fs), tok_dp)

    np.testing.assert_allclose(float(loss_fs), float(loss_rep), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_fs), jax.tree.leaves(p_rep)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)
    # Updated params keep their sharded placement — the 1/n memory claim
    # holds across steps, not just at initialization.
    assert sharded_fraction(p_fs) > 0.95


def test_fsdp_trains_multiple_steps():
    mesh = make_mesh(8, axis_name="dp")
    key = jax.random.PRNGKey(2)
    params = shard_params_fsdp(init_transformer(key, CFG), mesh)
    data = jax.random.randint(key, (8, 33), 0, CFG.vocab)
    tok = jax.device_put(data, NamedSharding(mesh, P("dp")))
    opt_init, step = make_lm_train_step(CFG, lr=3e-3)
    opt = opt_init(params)
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, tok)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    # lm_loss on the trained sharded params still evaluates fine
    assert np.isfinite(float(lm_loss(params, tok, CFG)))


def test_fsdp_half_mesh_axis():
    """FSDP over a 2-D (dp, sp) mesh's dp axis only: specs name just dp,
    so the same params compose with sequence parallelism on sp."""
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    params = shard_params_fsdp(init_transformer(jax.random.PRNGKey(3), CFG), mesh)
    assert sharded_fraction(params) > 0.9
    for leaf in jax.tree.leaves(params):
        spec = leaf.sharding.spec
        assert "sp" not in [s for s in spec if s is not None]


def test_fsdp_composes_with_ring_flash_sp():
    """ZeRO x context parallelism in ONE jitted step: params FSDP-sharded
    over 'dp', ring+flash attention over 'sp' on a (2, 4) mesh — loss and
    updated params equal the replicated single-device step, and the 1/n
    param placement survives the step."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "sp"))
    cfg = TransformerConfig(
        d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=64,
        attn_impl="ring", attn_engine="flash", sp_shards=4,
    )
    ref = TransformerConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=64)
    key = jax.random.PRNGKey(0)
    params = init_transformer(key, cfg)
    tokens = jax.random.randint(key, (4, 33), 0, cfg.vocab)  # L=32 = 4 shards x 8

    opt_init, step_ref = make_lm_train_step(ref, lr=1e-2)
    p_ref, _, loss_ref = step_ref(params, opt_init(params), tokens)

    fs = shard_params_fsdp(params, mesh)  # dp axis only (fsdp_spec default)
    tok = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    opt_init2, step_fs = make_lm_train_step(cfg, mesh=mesh, lr=1e-2)
    p_fs, _, loss_fs = step_fs(fs, opt_init2(fs), tok)

    np.testing.assert_allclose(float(loss_fs), float(loss_ref), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_fs), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)
    assert sharded_fraction(p_fs) > 0.95

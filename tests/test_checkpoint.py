"""Checkpoint tests: bit-exact roundtrips, cross-tier load, training state.

The capability the reference lacks (SURVEY §5.4): weights shared across
backends from one file rather than re-synthesized per version.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import forward_blocks12
from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
    deterministic_input,
    init_params_deterministic,
    init_params_random,
)
from cuda_mpi_gpu_cluster_programming_tpu.utils import checkpoint as ckpt


def test_npz_roundtrip_bit_exact(tmp_path):
    params = init_params_random(jax.random.PRNGKey(0))
    path = ckpt.save_params_npz(tmp_path / "w.npz", params)
    loaded = ckpt.load_params_npz(path)
    assert jax.tree_util.tree_structure(loaded) == jax.tree_util.tree_structure(params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(loaded)):
        assert np.array_equal(np.asarray(a), np.asarray(b))  # bit-exact


def test_npz_nested_and_list_trees(tmp_path):
    """List nodes survive the roundtrip as lists, so tree_map against the
    original structure works (the optimizer-state case)."""
    tree = {"opt": {"mu": [jnp.ones((2, 3)), jnp.zeros((4,))]}, "step": jnp.array(7)}
    loaded = ckpt.load_params_npz(ckpt.save_params_npz(tmp_path / "s.npz", tree))
    assert jax.tree_util.tree_structure(loaded) == jax.tree_util.tree_structure(tree)
    jax.tree_util.tree_map(lambda a, b: None, tree, loaded)  # no structure mismatch
    assert np.array_equal(np.asarray(loaded["opt"]["mu"][0]), np.ones((2, 3)))
    assert int(loaded["step"]) == 7


def test_npz_like_restores_exact_structure(tmp_path):
    """``like=`` restores tuples/namedtuple-style trees exactly."""
    tree = {"state": (jnp.arange(3.0), jnp.ones((2,)))}
    path = ckpt.save_params_npz(tmp_path / "t.npz", tree)
    loaded = ckpt.load_params_npz(path, like=tree)
    assert jax.tree_util.tree_structure(loaded) == jax.tree_util.tree_structure(tree)
    assert isinstance(loaded["state"], tuple)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        tree,
        loaded,
    )


def test_forward_from_checkpoint_matches_golden(tmp_path):
    """Weights loaded from disk drive the same golden forward numerics."""
    params = init_params_deterministic()
    loaded = ckpt.load_params_npz(ckpt.save_params_npz(tmp_path / "det.npz", params))
    out = jax.jit(forward_blocks12)(loaded, deterministic_input(1))
    flat = np.asarray(out[0]).reshape(-1)
    np.testing.assert_allclose(flat[:3], [29.29313, 25.915306, 23.325487], rtol=1e-5)


def test_orbax_roundtrip(tmp_path):
    params = init_params_random(jax.random.PRNGKey(1))
    d = ckpt.save_params_orbax(tmp_path / "orbax_ckpt", params)
    restored = ckpt.load_params_orbax(d, target=params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(restored)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_npz_optax_state_roundtrip(tmp_path):
    """Real optimizer state (namedtuple/dataclass nodes) saves and restores
    with like= into the exact original structure."""
    import optax

    params = init_params_random(jax.random.PRNGKey(2))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    path = ckpt.save_params_npz(tmp_path / "opt.npz", state)
    template = opt.init(params)  # fresh state of identical structure
    restored = ckpt.load_params_npz(path, like=template)
    assert jax.tree_util.tree_structure(restored) == jax.tree_util.tree_structure(state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state,
        restored,
    )


def test_orbax_restores_fsdp_sharded_placement(tmp_path):
    """Distributed checkpointing: an FSDP-sharded LM tree round-trips
    through orbax with BOTH values and NamedSharding placement intact —
    the multi-host-safe path npz (host-gather) cannot provide."""
    import jax

    from cuda_mpi_gpu_cluster_programming_tpu.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.fsdp import (
        shard_params_fsdp,
        sharded_fraction,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh

    cfg = TransformerConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128, max_len=32)
    mesh = make_mesh(8, axis_name="dp")
    params = shard_params_fsdp(init_transformer(jax.random.PRNGKey(0), cfg), mesh)
    d = ckpt.save_params_orbax(tmp_path / "fsdp_ckpt", params)
    restored = ckpt.load_params_orbax(d, target=params)
    assert sharded_fraction(restored) > 0.95
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------ crash consistency ---


def test_npz_save_is_atomic_on_crash(tmp_path, monkeypatch):
    """A crash mid-save must leave the PREVIOUS checkpoint intact — no
    truncated archive, no tmp residue (the last-good rollback contract)."""
    path = tmp_path / "w.npz"
    good = init_params_deterministic()
    ckpt.save_params_npz(path, good)
    before = path.read_bytes()

    def exploding_savez(fh, **kw):
        fh.write(b"partial garbage")
        raise RuntimeError("simulated crash mid-serialization")

    monkeypatch.setattr(ckpt.np, "savez", exploding_savez)
    try:
        ckpt.save_params_npz(path, good)
    except RuntimeError:
        pass
    assert path.read_bytes() == before  # old checkpoint untouched
    assert [f.name for f in tmp_path.iterdir()] == ["w.npz"]  # no tmp residue
    loaded = ckpt.load_params_npz(path)  # and it still loads
    assert set(loaded) == {"conv1", "conv2"}


def test_truncated_npz_load_raises_clear_value_error(tmp_path):
    """A torn file (pre-atomic-writer crash, failing medium) must raise one
    catchable ValueError naming the path, not leak zipfile internals."""
    import pytest

    path = ckpt.save_params_npz(tmp_path / "w.npz", init_params_deterministic())
    data = path.read_bytes()
    path.write_bytes(data[: len(data) // 2])  # truncate
    with pytest.raises(ValueError, match="truncated or corrupt"):
        ckpt.load_params_npz(path)
    path.write_bytes(b"")  # zero-length (kill at creation)
    with pytest.raises(ValueError, match="truncated or corrupt"):
        ckpt.load_params_npz(path)


def test_missing_checkpoint_still_file_not_found(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        ckpt.load_params_npz(tmp_path / "absent.npz")


def test_sharded_tree_roundtrip_and_gc(tmp_path):
    """Sharded-tree save/load: bit-exact roundtrip, leaves dealt across the
    requested shard files, stale generations GC'd after the commit."""
    params = init_params_random(jax.random.PRNGKey(3))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=3, meta={"step": 1})
    names = sorted(p.name for p in d.iterdir())
    assert names == [
        "MANIFEST.json",
        "shard_000.gen00000000.npz",
        "shard_001.gen00000000.npz",
        "shard_002.gen00000000.npz",
    ]
    tree, meta = ckpt.load_tree_sharded(d)
    assert meta == {"step": 1}
    assert jax.tree_util.tree_structure(tree) == jax.tree_util.tree_structure(params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(tree)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # Second generation replaces the first (post-commit GC).
    ckpt.save_tree_sharded(d, params, n_shards=3, meta={"step": 2})
    names = sorted(p.name for p in d.iterdir())
    assert names == [
        "MANIFEST.json",
        "shard_000.gen00000001.npz",
        "shard_001.gen00000001.npz",
        "shard_002.gen00000001.npz",
    ]
    assert ckpt.load_tree_sharded(d)[1] == {"step": 2}


def test_sharded_save_kill_mid_shard_write_keeps_last_good(tmp_path, monkeypatch):
    """A kill while writing shard k>0 of the new generation: the manifest
    still names the previous complete generation, which loads."""
    params = init_params_random(jax.random.PRNGKey(4))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=3, meta={"step": 1})
    calls = []
    orig = ckpt.np.savez

    def exploding_savez(fh, **kw):
        calls.append(1)
        if len(calls) >= 2:
            raise RuntimeError("simulated kill mid sharded save")
        return orig(fh, **kw)

    monkeypatch.setattr(ckpt.np, "savez", exploding_savez)
    with pytest.raises(RuntimeError):
        ckpt.save_tree_sharded(d, params, n_shards=3, meta={"step": 2})
    monkeypatch.undo()
    tree, meta = ckpt.load_tree_sharded(d)
    assert meta == {"step": 1}  # last-good generation
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(tree)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_sharded_save_kill_before_manifest_commit_keeps_last_good(
    tmp_path, monkeypatch
):
    """All new shard files written but the kill lands before the manifest
    replace (the commit point): the old manifest + old generation win, and
    the orphaned new-generation files are invisible."""
    params = init_params_random(jax.random.PRNGKey(5))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=2, meta={"step": 1})
    monkeypatch.setattr(
        ckpt,
        "atomic_write_text",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("kill pre-commit")),
    )
    with pytest.raises(RuntimeError):
        ckpt.save_tree_sharded(d, params, n_shards=2, meta={"step": 2})
    monkeypatch.undo()
    _tree, meta = ckpt.load_tree_sharded(d)
    assert meta == {"step": 1}
    # Orphaned gen-1 files exist on disk but the manifest never names them.
    manifest = json.loads((d / ckpt.MANIFEST_NAME).read_text())
    assert all(f.endswith(".gen00000000.npz") for f in manifest["files"])


def test_sharded_manifest_and_shard_corruption_raise_value_error(tmp_path):
    import pytest

    params = init_params_random(jax.random.PRNGKey(6))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=2)
    # Torn manifest (pre-atomic-writer crash / failing medium).
    good_manifest = (d / ckpt.MANIFEST_NAME).read_text()
    (d / ckpt.MANIFEST_NAME).write_text(good_manifest[: len(good_manifest) // 2])
    with pytest.raises(ValueError, match="manifest"):
        ckpt.load_tree_sharded(d)
    (d / ckpt.MANIFEST_NAME).write_text(good_manifest)
    # Truncated shard file.
    shard = d / json.loads(good_manifest)["files"][0]
    shard.write_bytes(shard.read_bytes()[:16])
    with pytest.raises(ValueError, match="truncated or corrupt"):
        ckpt.load_tree_sharded(d)
    # Missing directory entirely.
    with pytest.raises(FileNotFoundError):
        ckpt.load_tree_sharded(tmp_path / "absent")


def test_sharded_train_state_roundtrip_and_like_structures(tmp_path):
    """(params, opt_state, step) through the sharded format into the exact
    optimizer-state structure — the train CLI's --checkpoint-shards path."""
    import optax

    params = init_params_random(jax.random.PRNGKey(7))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    d = tmp_path / "state"
    ckpt.save_train_state_sharded(d, params, opt_state, step=9, n_shards=4)
    p2, o2, step = ckpt.load_train_state_sharded(d, params, opt.init(params))
    assert step == 9
    assert jax.tree_util.tree_structure(o2) == jax.tree_util.tree_structure(opt_state)
    for a, b in zip(jax.tree_util.tree_leaves(opt_state), jax.tree_util.tree_leaves(o2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(p2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_reshard_on_load_property_params(tmp_path):
    """ISSUE 8: an n-way sharded checkpoint reassembles BIT-identically
    onto n/2, 2n, and 1 target devices — the on-disk shard count is a
    property of the save, never a constraint on the restore."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh

    params = init_params_random(jax.random.PRNGKey(11))
    for n, m in [(4, 2), (2, 4), (4, 1), (3, 8)]:
        d = tmp_path / f"ck_{n}_{m}"
        ckpt.save_tree_sharded(d, params, n_shards=n, meta={"n": n})
        tree, meta = ckpt.load_tree_sharded(d, target_shards=m)
        assert meta == {"n": n}
        assert jax.tree_util.tree_structure(tree) == jax.tree_util.tree_structure(params)
        want = NamedSharding(make_mesh(m), P())
        for a, b in zip(
            jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(tree)
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))  # bit-exact
            assert b.sharding == want  # placed on the TARGET topology


def test_reshard_on_load_train_state(tmp_path):
    """The full train state (opt state included) restores onto n/2 and 2n
    shard counts bit-identically, placed replicated on the target mesh —
    the restore side of the elastic-mesh story."""
    import optax

    from cuda_mpi_gpu_cluster_programming_tpu.parallel.elastic import (
        tree_device_ids,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.mesh import make_mesh

    params = init_params_random(jax.random.PRNGKey(12))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    d = tmp_path / "state"
    ckpt.save_train_state_sharded(d, params, opt_state, step=5, n_shards=4)
    for m in (2, 8):
        p2, o2, step = ckpt.load_train_state_sharded(
            d, params, opt.init(params), target_shards=m
        )
        assert step == 5
        assert jax.tree_util.tree_structure(o2) == jax.tree_util.tree_structure(opt_state)
        for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(p2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(opt_state), jax.tree_util.tree_leaves(o2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        ids = {dev.id for dev in make_mesh(m).devices.flat}
        assert tree_device_ids(p2) == ids and tree_device_ids(o2) == ids
    # mesh= places onto an explicit (e.g. surviving-device) mesh directly.
    mesh = make_mesh(2, devices=jax.devices()[4:])
    p3, _o3, _ = ckpt.load_train_state_sharded(d, params, opt.init(params), mesh=mesh)
    assert tree_device_ids(p3) == {dev.id for dev in mesh.devices.flat}


def test_shard_layout_derivable_from_manifest_alone(tmp_path):
    """The manifest's (n_shards, key order) fully determines the
    round-robin layout: shard_layout opens no shard file, yet names the
    exact file holding every leaf."""
    import numpy as onp

    params = init_params_random(jax.random.PRNGKey(13))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=3)
    layout = ckpt.shard_layout(d)
    # Verify against the actual shard contents.
    actual = {}
    for f in json.loads((d / ckpt.MANIFEST_NAME).read_text())["files"]:
        with onp.load(d / f) as archive:
            for k in archive.files:
                actual[k] = f
    assert layout == actual
    # Pre-keys (v1) manifests refuse derivation attributably.
    manifest = json.loads((d / ckpt.MANIFEST_NAME).read_text())
    del manifest["keys"]
    (d / ckpt.MANIFEST_NAME).write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="keys"):
        ckpt.shard_layout(d)


def test_missing_shard_files_raise_attributable_error(tmp_path):
    """ISSUE 8 bugfix: a partially-GC'd/hand-pruned directory names the
    manifest-declared shard set vs. what the directory holds — not a
    medium-blaming ValueError, and never a bare KeyError on the like=
    path."""
    params = init_params_random(jax.random.PRNGKey(14))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=3)
    victim = json.loads((d / ckpt.MANIFEST_NAME).read_text())["files"][1]
    (d / victim).unlink()  # the partially-GC'd directory
    with pytest.raises(ValueError, match="n_shards=3") as ei:
        ckpt.load_tree_sharded(d)
    assert victim in str(ei.value) and "pruned outside the saver" in str(ei.value)
    # like= takes the same attributable path (previously a KeyError).
    with pytest.raises(ValueError, match="missing"):
        ckpt.load_tree_sharded(d, like=params)
    # A manifest whose file list disagrees with its own n_shards is called
    # malformed, with both numbers.
    manifest = json.loads((d / ckpt.MANIFEST_NAME).read_text())
    manifest["files"] = manifest["files"][:2]
    (d / ckpt.MANIFEST_NAME).write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="declares n_shards=3 but names 2"):
        ckpt.load_tree_sharded(d)


def test_extra_overlapping_shard_content_raises(tmp_path):
    """A manifest naming the same shard twice (foreign/extra content) is an
    attributable duplicate-leaf error, not silent double-assignment."""
    params = init_params_random(jax.random.PRNGKey(15))
    d = tmp_path / "ck"
    ckpt.save_tree_sharded(d, params, n_shards=2)
    manifest = json.loads((d / ckpt.MANIFEST_NAME).read_text())
    manifest["files"] = [manifest["files"][0]] + manifest["files"]
    manifest["n_shards"] = 3
    (d / ckpt.MANIFEST_NAME).write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="more than one shard file"):
        ckpt.load_tree_sharded(d)


def test_train_state_roundtrip_sgd_and_adam(tmp_path):
    """(params, opt_state, step) survive the roundtrip bit-exact into the
    exact optimizer-state structure (tuples/namedtuples need like=)."""
    import optax

    params = init_params_random(jax.random.PRNGKey(1))
    for name, opt in (("sgd", optax.sgd(1e-3)), ("adam", optax.adam(1e-3))):
        opt_state = opt.init(params)
        path = tmp_path / f"state_{name}.npz"
        ckpt.save_train_state(path, params, opt_state, step=17)
        p2, o2, step = ckpt.load_train_state(path, params, opt_state)
        assert step == 17
        assert jax.tree_util.tree_structure(o2) == jax.tree_util.tree_structure(opt_state)
        for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(p2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(opt_state), jax.tree_util.tree_leaves(o2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

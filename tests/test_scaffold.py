"""Scaffolding CLI tests (scaffold_hw.sh / test_hw.sh / package_hw.sh analogues).

The generated template must be runnable as-is and self-verify (the course
templates compile as-is); the sweep runner must implement test_hw.sh's
skip/timeout/exit-code semantics (:8-10,113-180); packaging must follow the
hwN-last-first naming (package_hw.sh:11-21).
"""

import tarfile

import pytest

from cuda_mpi_gpu_cluster_programming_tpu.scaffold import (
    PASSED,
    SKIPPED,
    cmd_new,
    cmd_package,
    cmd_test,
    run_case,
)


@pytest.fixture()
def hw(tmp_path):
    cmd_new(tmp_path, 1)
    return tmp_path


def test_new_generates_files(hw):
    assert (hw / "hw1" / "src" / "template.py").exists()
    assert (hw / "hw1" / "summary.md").exists()
    text = (hw / "hw1" / "src" / "template.py").read_text()
    assert "hw1" in text and "{HW_NUM}" not in text


def test_new_refuses_overwrite(hw, capsys):
    marker = "# my edit\n"
    f = hw / "hw1" / "src" / "template.py"
    f.write_text(f.read_text() + marker)
    cmd_new(hw, 1)
    assert marker in f.read_text()
    assert "skip (exists)" in capsys.readouterr().out


def test_generated_template_passes(hw):
    entry = hw / "hw1" / "src" / "template.py"
    status, wall, detail = run_case(entry, 128, 2, timeout_s=120.0)
    assert status == PASSED, detail


def test_run_case_skips_nondivisible(hw):
    entry = hw / "hw1" / "src" / "template.py"
    assert run_case(entry, 128, 3, timeout_s=120.0)[0] == SKIPPED


def test_sweep_exit_codes(hw):
    # Trim the matrix for test speed; semantics are what's under test.
    rc = cmd_test(hw, 1, sizes=(128,), np_counts=(1, 3), timeout_s=120.0)
    assert rc == 0  # np=3 skipped, np=1 passed
    entry = hw / "hw1" / "src" / "template.py"
    entry.write_text(entry.read_text().replace("Test: PASSED", "Test: BROKEN"))
    assert cmd_test(hw, 1, sizes=(128,), np_counts=(1,), timeout_s=120.0) == 1


def test_sweep_missing_experiment(tmp_path):
    assert cmd_test(tmp_path, 9, sizes=(128,), np_counts=(1,)) == 1


def test_package_naming_and_contents(hw):
    archive = cmd_package(hw, 1, "Doe", "Jane")
    assert archive.name == "hw1-doe-jane.tgz"
    with tarfile.open(archive) as tf:
        names = tf.getnames()
    assert "hw1-doe-jane/src/template.py" in names
    assert "hw1-doe-jane/summary.md" in names


def test_package_missing_source(tmp_path):
    with pytest.raises(FileNotFoundError):
        cmd_package(tmp_path, 2, "doe", "jane")

"""Network serving front end tests (ISSUE 11, docs/SERVING.md "Network
front end & SLOs") — CPU, virtual 8-device mesh.

Covers the tentpole surface: the HTTP transport honoring the admission
queue contract exactly (429 backpressure, 413 oversize, 400 malformed,
504 explicit shed, 200 with reference-exact outputs), per-request
``serve.transport`` spans + ``serve_transport``/``serve_reject`` journal
records, traffic shapes (seeded diurnal/burst/flash arrivals, heavy-
tailed class mixes), SLO-aware shed-by-class under a flash crowd with
per-class CLOSED accounting, the ``QueueStats.oldest_wait_ms`` gauge,
the saturation sweep's p99 knee with journal==registry percentile
agreement, and the chaos drills riding through the front end unchanged.
"""

import dataclasses
import http.client
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import (
    BLOCKS12,
    forward_blocks12,
)
from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
    init_params_deterministic,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability.export import (
    to_trace_events,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability.metrics import (
    registry as metrics_registry,
)
from cuda_mpi_gpu_cluster_programming_tpu.observability.trace import (
    Tracer,
    set_tracer,
)
from cuda_mpi_gpu_cluster_programming_tpu.resilience import chaos
from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal
from cuda_mpi_gpu_cluster_programming_tpu.serving.frontend import (
    ServingFrontend,
    http_fleet_load,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.loadgen import (
    locate_knee,
    percentile,
    run_shaped_load,
    saturation_sweep,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.queue import (
    OK,
    SHED,
    AdmissionQueue,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.server import (
    InferenceServer,
    ServeConfig,
    class_latencies_from_journal,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.slo import SLOClass, SLOPolicy
from cuda_mpi_gpu_cluster_programming_tpu.serving.traffic import (
    RequestClass,
    default_class_mix,
    parse_shape,
    shaped_arrivals,
    slo_policy,
)

ROOT = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)
IMG_SHAPE = (CFG.in_height, CFG.in_width, CFG.in_channels)


def _img(v: float = 1.0, n: int = 1) -> np.ndarray:
    return np.full((n, *IMG_SHAPE), v, np.float32)


@pytest.fixture(autouse=True)
def _clean_process_state(monkeypatch):
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.reset()
    metrics_registry().reset()
    yield
    set_tracer(None)
    chaos.reset()


def _post(fe, payload, timeout=60.0):
    conn = http.client.HTTPConnection(fe.host, fe.port, timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/infer", json.dumps(payload),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(fe, path, timeout=30.0):
    conn = http.client.HTTPConnection(fe.host, fe.port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _wait_records(jpath, kind, n, timeout_s=10.0):
    """Journal writes land in @off_timed_path finishers AFTER the client
    already has its response — poll (bounded) so assertions read a
    settled trail instead of racing the writer thread."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        recs = [r for r in Journal.load(jpath) if r["kind"] == kind]
        if len(recs) >= n:
            return recs
        time.sleep(0.01)
    return [r for r in Journal.load(jpath) if r["kind"] == kind]


# ------------------------------------------------------------ transport ---


def test_http_roundtrip_matches_reference():
    """An inference request over the wire returns EXACTLY what the
    in-process forward returns — the transport adds a socket, never a
    numeric."""
    srv = InferenceServer(ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG))
    srv.start()
    fe = ServingFrontend(srv).start()
    try:
        x = _img(1.25, n=2)
        code, body = _post(
            fe,
            {
                "shape": list(x.shape),
                "data": x.reshape(-1).tolist(),
                "return_output": True,
            },
        )
        assert code == 200 and body["status"] == OK
        params = init_params_deterministic(CFG)
        want = np.asarray(jax.jit(lambda p, a: forward_blocks12(p, a, CFG))(params, x))
        got = np.asarray(body["output"], np.float32).reshape(body["output_shape"])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert body["latency_ms"] > 0
    finally:
        fe.stop()
        srv.stop()
    assert srv.stats.cache_misses == 0


def test_http_healthz_and_stats_expose_queue_gauges():
    srv = InferenceServer(ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG))
    fe = ServingFrontend(srv).start()
    try:
        srv.submit(_img())  # parked: dispatch loop not running
        time.sleep(0.02)
        code, body = _get(fe, "/healthz")
        assert code == 200 and body["status"] == "ok"
        qs = body["queue"]
        assert qs["depth"] == 1 and qs["pending_images"] == 1
        assert qs["oldest_wait_ms"] > 0  # saturation visible pre-shed
        code, body = _get(fe, "/stats")
        assert code == 200 and "queue" in body and "http" in body
        # no controller configured => no controller key: the pre-ISSUE-18
        # probe payload shape, exactly
        assert "controller" not in body
        code, _ = _get(fe, "/nope")
        assert code == 404
    finally:
        fe.stop()


def test_http_healthz_and_stats_expose_controller_state():
    """ISSUE 18 satellite: with the Autopilot attached, /healthz and
    /stats carry its state snapshot (mode, level, active overrides, last
    action + age) — the router's probes see degraded-but-healthy instead
    of inferring it from latency."""
    from cuda_mpi_gpu_cluster_programming_tpu.serving.controller import (
        ControllerConfig,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.serving.traffic import (
        default_class_mix,
        slo_policy,
    )

    mix = list(default_class_mix([1, 2, 4]))
    srv = InferenceServer(ServeConfig(
        config="v1_jit", max_batch=4, model_cfg=CFG,
        slo=slo_policy(mix), controller=ControllerConfig(),
    ))
    fe = ServingFrontend(srv).start()
    try:
        for path in ("/healthz", "/stats"):
            code, body = _get(fe, path)
            assert code == 200
            ctl = body["controller"]
            assert ctl["mode"] == "steady" and ctl["level"] == 0
            assert ctl["overrides"] == [] and ctl["last_action"] is None
        # a degraded controller is visible through the same window
        for _ in range(srv.controller.cfg.min_completed):
            srv.controller.note_shed("interactive")
        srv.controller.evaluate(now=1e9)
        code, body = _get(fe, "/healthz")
        ctl = body["controller"]
        assert ctl["mode"] == "degraded" and ctl["level"] == 1
        assert ctl["overrides"][0]["action"] == "tighten_admission"
        assert ctl["last_action"]["action"] == "tighten_admission"
        assert "age_s" in ctl["last_action"]
    finally:
        fe.stop()


def test_http_metrics_prometheus_exposition(tmp_path):
    """ISSUE 13 satellite: GET /metrics serves the process-wide registry
    in Prometheus text exposition (counters, gauges, histogram summaries
    with the repo's nearest-rank percentiles), and the scrape is
    journaled as a serve_transport record like every POST exchange."""
    jp = tmp_path / "serve.jsonl"
    srv = InferenceServer(
        ServeConfig(
            config="v1_jit", max_batch=4, model_cfg=CFG,
            journal_path=str(jp),
        )
    )
    srv.start()
    fe = ServingFrontend(srv).start()
    try:
        code, body = _post(fe, {"shape": [1, *IMG_SHAPE], "fill": 1.0})
        assert code == 200
        conn = http.client.HTTPConnection(fe.host, fe.port, timeout=30)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            assert resp.status == 200
            assert resp.getheader("Content-Type").startswith(
                "text/plain; version=0.0.4"
            )
        finally:
            conn.close()
        lines = text.splitlines()
        assert "# TYPE serve_ok counter" in lines
        assert any(l.startswith("serve_ok ") for l in lines)
        assert "# TYPE serve_request_ms summary" in lines
        assert any('serve_request_ms{quantile="0.5"}' in l for l in lines)
        assert any(l.startswith("serve_request_ms_count") for l in lines)
        # the registry's dotted names sanitize to the exposition grammar
        assert not any("." in l.split("{")[0].split(" ")[0] for l in lines
                       if l and not l.startswith("#"))
    finally:
        fe.stop()
        srv.stop()
    recs = _wait_records(jp, "serve_transport", 2)
    assert any(r.get("status") == "METRICS" for r in recs)


def test_http_backpressure_oversize_and_malformed():
    """The admission contract on the wire: QueueFull -> 429 (+Retry-After),
    wider than the largest bucket -> 413, malformed body -> 400; every
    refusal journals a serve_reject record."""
    import tempfile

    jpath = tempfile.mktemp(suffix=".jsonl")
    srv = InferenceServer(
        ServeConfig(config="v1_jit", max_batch=2, max_pending=1,
                    model_cfg=CFG, journal_path=jpath)
    )
    fe = ServingFrontend(srv).start()
    try:
        srv.submit(_img())  # fills max_pending=1; dispatch loop not running
        code, body = _post(fe, {"shape": [1, *IMG_SHAPE], "fill": 1.0})
        assert code == 429 and body["status"] == "REJECTED"
        assert "max_pending" in body["error"]
        code, body = _post(fe, {"shape": [5, *IMG_SHAPE], "fill": 1.0})
        assert code == 413 and "largest bucket" in body["error"]
        code, body = _post(fe, {"shape": "nope"})
        assert code == 400 and body["status"] == "REJECTED"
        code, body = _post(fe, {"shape": [1, *IMG_SHAPE], "data": [1.0, 2.0]})
        assert code == 400  # wrong element count
    finally:
        fe.stop()
    rejects = _wait_records(jpath, "serve_reject", 4)
    assert sorted(r["http"] for r in rejects) == [400, 400, 413, 429]


def test_http_shed_answers_504_with_reason():
    """A queue shed is an explicit wire verdict: 504 + the reason — the
    client always learns what happened, nothing is silently dropped."""
    srv = InferenceServer(
        ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG)
    ).start()
    fe = ServingFrontend(srv).start()
    try:
        code, body = _post(
            fe, {"shape": [1, *IMG_SHAPE], "fill": 1.0, "deadline_s": 1e-6}
        )
        assert code == 504
        assert body["status"] == SHED and body["reason"] == "deadline"
    finally:
        fe.stop()
        srv.stop()


def test_http_fleet_diurnal_burst_end_to_end(tmp_path):
    """THE acceptance drill: a threaded HTTP client fleet drives a
    diurnal+burst shape through the front end — per-class accounting
    closes, zero post-warmup cache misses, per-class p99s come out of the
    journal, every exchange has a serve.transport span + serve_transport
    record, and the whole journal exports into one Perfetto timeline."""
    jpath = tmp_path / "serve.jsonl"
    mix = list(default_class_mix((1, 2, 4)))
    scfg = ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                       journal_path=str(jpath), slo=slo_policy(mix))
    srv = InferenceServer(scfg)
    tracer = Tracer(journal=srv.journal)
    set_tracer(tracer)
    srv.start()
    fe = ServingFrontend(srv).start()
    try:
        report = http_fleet_load(
            fe.url, IMG_SHAPE,
            shape="diurnal:amp=0.8,period=0.6+burst:every=0.3,mult=4",
            rate_rps=35.0, duration_s=0.6, classes=mix, seed=11, n_workers=6,
        )
    finally:
        fe.stop()
        srv.stop()
        set_tracer(None)
    assert report.n_requests > 0 and report.n_ok > 0
    assert report.closed  # ok+shed+failed+rejected == offered, PER CLASS
    assert srv.stats.cache_misses == 0
    _wait_records(
        jpath, "serve_transport",
        report.n_ok + report.n_shed + report.n_failed,
    )
    recs = Journal.load(jpath)
    # per-class p99s from the journal: every OK request's latency lands
    # under its class
    by_cls = class_latencies_from_journal(jpath)
    assert sum(len(v) for v in by_cls.values()) == report.n_ok
    for name, stats in report.per_class.items():
        if stats.ok:
            lats = by_cls[name]
            assert len(lats) == stats.ok
            assert percentile(lats, 99) > 0
    # transport records: one per non-rejected HTTP exchange, spans beside
    transports = [r for r in recs if r["kind"] == "serve_transport"]
    assert len(transports) == report.n_ok + report.n_shed + report.n_failed
    assert all(r["span_id"] for r in transports)
    span_names = {r["name"] for r in recs if r["kind"] == "span"}
    assert "serve.transport" in span_names and "serve.dispatch" in span_names
    # the export stitches the new kinds onto the serve lane
    trace = to_trace_events(recs)
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert "serve.transport" in names and "serve_transport" in names


def test_http_chaos_device_loss_drill_through_frontend(tmp_path, monkeypatch):
    """The PR 6 chaos drill with the front end ATTACHED: a seeded device
    loss mid-load trips the supervisor, the in-flight batch replays down
    the ladder, and every HTTP client still gets a 200 — degradation
    stays invisible to the wire except in latency."""
    jpath = tmp_path / "serve.jsonl"
    monkeypatch.setenv(chaos.CHAOS_ENV, "seed=3,device_loss=1")
    chaos.reset()
    srv = InferenceServer(
        ServeConfig(config="v2.2_sharded", n_shards=2, max_batch=4,
                    supervise=True, model_cfg=CFG, journal_path=str(jpath))
    ).start()
    fe = ServingFrontend(srv).start()
    try:
        codes = []
        for i in range(4):
            code, body = _post(
                fe,
                {"shape": [1, *IMG_SHAPE], "fill": 1.0 + 0.01 * i,
                 "class": "interactive"},
                timeout=120.0,
            )
            codes.append((code, body["status"]))
    finally:
        fe.stop()
        srv.stop()
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()
    assert codes == [(200, OK)] * 4  # nobody 500s through a device loss
    assert [t.kind for t in srv.sup.trips] == ["device_loss"]
    assert srv.stats.cache_misses == 0  # re-warm kept the discipline
    kinds = [r["kind"] for r in Journal.load(jpath)]
    assert "sup_trip" in kinds and "serve_rewarm" in kinds
    assert kinds.index("serve_rewarm") < kinds.index("serve_batch")


# ------------------------------------------------------- traffic shapes ---


def test_shaped_arrivals_deterministic_and_sorted():
    for spec in ("steady", "diurnal", "burst", "flash", "diurnal+burst"):
        a = shaped_arrivals(spec, 80.0, 1.0, seed=5)
        assert a == shaped_arrivals(spec, 80.0, 1.0, seed=5)
        assert a == sorted(a) and all(0 <= t < 1.0 for t in a)
        assert shaped_arrivals(spec, 80.0, 1.0, seed=6) != a
    assert shaped_arrivals("steady", 0.0, 1.0) == []


def test_shaped_arrivals_shapes_actually_shape():
    # diurnal phased to start at the trough: the second half of one
    # period carries more arrivals than the first
    a = shaped_arrivals("diurnal:amp=0.9,period=4", 200.0, 4.0, seed=0)
    assert len([t for t in a if t < 2.0]) < len([t for t in a if t >= 2.0])
    # burst/flash ADD traffic on top of the steady base
    base = len(shaped_arrivals("steady", 100.0, 2.0, seed=1))
    burst = len(shaped_arrivals("burst:every=0.5,mult=6,width=0.1", 100.0, 2.0, seed=1))
    flash = len(shaped_arrivals("flash:at=0.5,mult=10,width=0.2", 100.0, 2.0, seed=1))
    assert burst > base and flash > base
    # the flash crowd clumps around its epicenter
    fa = shaped_arrivals("flash:at=0.5,mult=20,width=0.1", 50.0, 2.0, seed=2)
    in_window = [t for t in fa if 1.0 <= t <= 1.3]
    assert len(in_window) > len(fa) / 2


def test_parse_shape_rejects_typos_loudly():
    with pytest.raises(ValueError, match="unknown traffic shape"):
        parse_shape("diurnall")
    with pytest.raises(ValueError, match="not key=number"):
        parse_shape("burst:every=lots")
    assert [c.kind for c in parse_shape("diurnal+burst")] == ["diurnal", "burst"]


def test_default_class_mix_is_heavy_tailed_over_buckets():
    mix = default_class_mix((1, 2, 4, 8))
    assert [c.name for c in mix] == ["interactive", "batch", "bulk"]
    inter, batch, bulk = mix
    assert inter.weight > batch.weight > bulk.weight
    assert inter.sizes == (1,) and bulk.sizes == (8,)
    assert set(batch.sizes) == {2, 4}
    assert inter.slo_ms < batch.slo_ms
    assert bulk.slo_ms == 0.0  # unbounded: never SLO-shed


# ------------------------------------------------------------ SLO layer ---


def test_slo_policy_sheds_by_class_not_by_age():
    pol = SLOPolicy(
        [SLOClass("tight", slo_ms=50.0), SLOClass("loose", slo_ms=5000.0)]
    )
    # same age, different verdicts: the class (not the age alone) decides
    assert pol.should_shed("tight", 80.0) == "slo"
    assert pol.should_shed("loose", 80.0) is None
    assert pol.should_shed("tight", 10.0) is None
    # unknown/unclassed requests keep PR 6 semantics: never SLO-shed
    assert pol.should_shed("", 1e9) is None
    assert pol.should_shed("mystery", 1e9) is None
    assert pol.deadline_for("tight") is None
    pol2 = SLOPolicy([SLOClass("d", slo_ms=100.0, deadline_s=0.5)])
    assert pol2.deadline_for("d") == 0.5


def test_queue_stats_oldest_wait_gauge():
    """ISSUE 11 satellite: saturation is observable BEFORE the first shed
    — depth, pending images, per-class depths, and the FIFO head's age."""
    q = AdmissionQueue()
    assert q.stats().oldest_wait_ms == 0.0 and q.stats().depth == 0
    q.submit(_img(n=2), cls="batch")
    q.submit(_img(), cls="interactive")
    time.sleep(0.02)
    qs = q.stats()
    assert qs.depth == 2 and qs.pending_images == 3
    assert qs.per_class == {"batch": 1, "interactive": 1}
    assert qs.oldest_wait_ms >= 20.0  # the head has waited at least the sleep
    obj = qs.to_obj()
    assert obj["oldest_wait_ms"] == round(qs.oldest_wait_ms, 3)
    q.pop_ready(max_images=8)
    qs2 = q.stats()
    assert qs2.depth == 0 and qs2.pending_images == 0
    assert qs2.oldest_wait_ms == 0.0 and qs2.per_class == {}


def test_flash_crowd_sheds_by_class_accounting_closes(tmp_path):
    """ISSUE 11 satellite: under a flash crowd, the tight-SLO class sheds
    (reason="slo", journaled with its class) while the unbounded class
    completes — and accounting closes PER CLASS: ok + shed + failed +
    rejected == offered for every class."""
    jpath = tmp_path / "serve.jsonl"
    mix = [
        RequestClass("tight", 0.6, (1,), (1.0,), deadline_s=None, slo_ms=40.0),
        RequestClass("loose", 0.4, (2,), (1.0,), deadline_s=None, slo_ms=0.0),
    ]
    scfg = ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                       journal_path=str(jpath), slo=slo_policy(mix))
    srv = InferenceServer(scfg).start()
    try:
        report = run_shaped_load(
            srv, shape="flash:at=0.2,mult=40,width=0.2", rate_rps=40.0,
            duration_s=0.5, classes=mix, seed=9,
        )
    finally:
        srv.stop()
    assert report.closed  # the satellite's acceptance: closes per class
    tight, loose = report.per_class["tight"], report.per_class["loose"]
    assert tight.offered > 0 and loose.offered > 0
    assert tight.shed > 0  # the flash crowd blew the 40 ms budget
    assert loose.shed == 0 and loose.failed == 0  # unbounded class rode it out
    assert srv.stats.cache_misses == 0
    sheds = [r for r in Journal.load(jpath) if r["kind"] == "serve_shed"]
    assert len(sheds) == report.n_shed
    assert all(r["reason"] == "slo" and r["cls"] == "tight" for r in sheds)
    assert all(r["waited_ms"] > 40.0 for r in sheds)
    # the metrics registry saw it too, attributably
    assert metrics_registry().counter("serve.shed_slo").value == report.n_shed
    # and saturation was observable before the shed: the gauge moved
    gauge = metrics_registry().gauge("serve.queue_oldest_wait_ms")
    assert gauge.value is not None


# ------------------------------------------------------ saturation study ---


def test_saturation_sweep_finds_knee_and_percentiles_agree(tmp_path):
    """The in-process saturation study: sweep past CPU capacity, locate
    the p99 knee, close accounting at every rate, and agree between the
    journal slice and the metrics-registry histogram (same estimator,
    same population)."""
    jpath = tmp_path / "serve.jsonl"
    mix = list(default_class_mix((1, 2, 4)))
    scfg = ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                       journal_path=str(jpath), slo=slo_policy(mix))
    srv = InferenceServer(scfg).start()
    try:
        rows = saturation_sweep(
            srv, [25.0, 500.0], duration_s=0.4, classes=mix, seed=5,
            journal_path=str(jpath),
        )
    finally:
        srv.stop()
    assert len(rows) == 2
    low, high = rows
    assert low["rate_rps"] == 25.0 and high["rate_rps"] == 500.0
    for r in rows:
        assert r["accounting_closed"] is True
        assert r["cache_misses"] == 0
        assert r["percentiles_agree"] is True
        assert r["knee_rate_img_s"] == high["offered_img_s"]  # knee located
        assert set(r["classes"]) == {"interactive", "batch", "bulk"}
    assert high["p99_ms"] > 3.0 * low["p99_ms"]  # the knee is real
    # reproducible under the fixed seed: the offered schedule is identical
    assert low["offered"] == len(shaped_arrivals("steady", 25.0, 0.4, 5))


def test_locate_knee_edge_cases():
    rows = [
        {"offered_img_s": 10.0, "p99_ms": 10.0},
        {"offered_img_s": 20.0, "p99_ms": 12.0},
        {"offered_img_s": 40.0, "p99_ms": 100.0},
    ]
    assert locate_knee(rows, 3.0) == 40.0
    assert locate_knee(rows[:2], 3.0) is None  # never crossed: no knee
    assert locate_knee([], 3.0) is None
    assert locate_knee([{"offered_img_s": 1.0, "p99_ms": None}], 3.0) is None


# ----------------------------------------------------------- CLI surfaces ---


def test_run_cli_serve_frontend_traffic_shape_smoke(tmp_path):
    """run --serve --serve-frontend 0 --traffic-shape: the whole network
    path from socket to shard_map under a shaped HTTP client fleet, with
    the machine-parsed frontend/class/transport lines."""
    jpath = tmp_path / "serve.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
         "--config", "v1_jit", "--serve", "--serve-frontend", "0",
         "--traffic-shape", "diurnal+burst", "--serve-rate", "25",
         "--serve-duration", "0.5", "--serve-max-batch", "4",
         "--height", "63", "--width", "63",
         "--serve-journal", str(jpath)],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.splitlines()
    assert any(l.startswith("Serve frontend: url=http://") for l in lines)
    load = next(l for l in lines if l.startswith("Serve load: "))
    assert "shape=diurnal+burst" in load and "rejected=" in load
    cls_lines = [l for l in lines if l.startswith("Serve class: ")]
    assert len(cls_lines) == 3  # interactive / batch / bulk
    assert any(l.startswith("Serve transport: http_200=") for l in lines)
    serve = next(l for l in lines if l.startswith("Serve: "))
    assert "cache_misses=0" in serve
    # the journal carries the transport records beside the batches
    kinds = {r["kind"] for r in Journal.load(jpath)}
    assert "serve_transport" in kinds and "serve_batch" in kinds


def test_run_cli_rejects_bad_traffic_shape():
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
         "--config", "v1_jit", "--serve", "--traffic-shape", "tsunami"],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "unknown traffic shape" in proc.stderr


def test_bench_saturate_mode_cpu_smoke(tmp_path):
    """BENCH_MODE=saturate tier-1 smoke: one JSON row per swept rate,
    accounting closed, journal==registry percentiles, zero cache misses,
    and the p99 knee located (the sweep crossed CPU capacity)."""
    jpath = tmp_path / "saturate.jsonl"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_MODE": "saturate",
        "BENCH_SERVE_HEIGHT": "63",
        "BENCH_SERVE_WIDTH": "63",
        "BENCH_SERVE_MAX_BATCH": "4",
        "BENCH_SAT_RATES": "30,600",
        "BENCH_SAT_DURATION": "0.6",
        "BENCH_SERVE_JOURNAL": str(jpath),
        "BENCH_SERVE_SEED": "7",
    }
    proc = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        cwd=ROOT, timeout=540, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(rows) == 2
    for row in rows:
        assert row["metric"] == "alexnet_blocks12_serve_saturation"
        assert "error" not in row
        assert row["accounting_closed"] is True
        assert row["percentiles_agree"] is True
        assert row["cache_misses"] == 0
        assert row["cache_misses_post_warmup"] == 0
        assert row["seed"] == 7
        assert row["knee_rate_img_s"] is not None  # knee located
        assert row["trace_id"]
    low, high = sorted(rows, key=lambda r: r["rate_rps"])
    assert high["p99_ms"] > 3.0 * low["p99_ms"]
    assert high["knee_rate_img_s"] == high["offered_img_s"]
    # the journal backs the rows: batches + SLO sheds landed there
    kinds = {r["kind"] for r in Journal.load(jpath)}
    assert "serve_batch" in kinds

"""Shard-vs-single equivalence: the test the reference never passed.

The reference's np>1 runs are numerically incomplete (V2.2 np=4 gathers
33,280 of 43,264 values; V4 np=2/4 gather 8/4 of 13 rows). Here the
row-sharded pipeline must reproduce the single-device output exactly, for
every shard count, on the non-divisible H=227 (227 = 8*29 - 5), both halo
transports, and batch > 1.
"""

import dataclasses

import jax
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models import (
    BLOCKS12,
    deterministic_input,
    forward_blocks12,
    init_params_deterministic,
    init_params_random,
    random_input,
)
from cuda_mpi_gpu_cluster_programming_tpu.parallel.plan import make_shard_plan, owned_range
from cuda_mpi_gpu_cluster_programming_tpu.parallel.sharded import build_sharded_forward


@pytest.fixture(scope="module")
def single_out():
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    return np.asarray(jax.jit(forward_blocks12)(params, x))


def test_plan_covers_all_rows():
    for n in (1, 2, 3, 4, 5, 8):
        plan = make_shard_plan(BLOCKS12, n)
        for lp in plan.layers:
            covered = []
            for i in range(n):
                s, e = owned_range(lp.b_out, lp.l_out, i)
                covered.extend(range(s, min(e, lp.l_out)))
            assert covered == list(range(lp.l_out)), (n, lp.name)


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_sharded_matches_single_deterministic(n, single_out):
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    fwd = build_sharded_forward(BLOCKS12, n_shards=n)
    out = np.asarray(fwd(params, x))
    assert out.shape == single_out.shape
    np.testing.assert_allclose(out, single_out, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n", [2, 8])
def test_sharded_matches_single_random(n):
    key = jax.random.PRNGKey(123)
    kp, kx = jax.random.split(key)
    params = init_params_random(kp)
    x = random_input(kx, batch=2)
    want = np.asarray(jax.jit(forward_blocks12)(params, x))
    got = np.asarray(build_sharded_forward(BLOCKS12, n_shards=n)(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4])
def test_staged_halo_matches_single(n, single_out):
    """V4-analogue transport (all_gather staging) must be numerically identical."""
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    got = np.asarray(build_sharded_forward(BLOCKS12, n_shards=n, staged=True)(params, x))
    np.testing.assert_allclose(got, single_out, rtol=1e-6, atol=1e-6)


def test_odd_shard_counts():
    """227 rows over 3 and 5 shards (uneven remainders, 2.2:main.cpp:103-109)."""
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    want = np.asarray(jax.jit(forward_blocks12)(params, x))
    for n in (3, 5):
        got = np.asarray(build_sharded_forward(BLOCKS12, n_shards=n)(params, x))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_small_image_sharded():
    """Non-default geometry through the planner (H=W=63)."""
    cfg = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)
    params = init_params_deterministic(cfg)
    key = jax.random.PRNGKey(5)
    x = jax.random.uniform(key, (2, 63, 63, 3))
    want = np.asarray(jax.jit(lambda p, v: forward_blocks12(p, v, cfg))(params, x))
    got = np.asarray(build_sharded_forward(cfg, n_shards=4)(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("staged", [False, True])
def test_pallas_tier_inside_sharded(staged, single_out):
    """v4_hybrid / v5_collective: Pallas kernels per shard (interpret mode on
    CPU). Regression: pallas_call inside shard_map requires check_vma=False."""
    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    fwd = build_sharded_forward(BLOCKS12, n_shards=4, tier="pallas", staged=staged)
    got = np.asarray(fwd(params, x))
    np.testing.assert_allclose(got, single_out, rtol=1e-5, atol=1e-5)


def test_multihop_halo_tiny_layers():
    """8 shards on a 63x63 image: conv2 sees only 6 rows (<1 per shard), so
    halos must hop multiple neighbors. The reference architecture cannot
    express this at all (immediate-neighbor Isend/Irecv only)."""
    cfg = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)
    params = init_params_deterministic(cfg)
    key = jax.random.PRNGKey(11)
    x = jax.random.uniform(key, (1, 63, 63, 3))
    want = np.asarray(jax.jit(lambda p, v: forward_blocks12(p, v, cfg))(params, x))
    got = np.asarray(build_sharded_forward(cfg, n_shards=8)(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sharded_forward_is_differentiable():
    """ppermute/dynamic_slice path must support reverse-mode autodiff —
    this is the spatial-parallel training path (GSPMD's is broken)."""
    import jax.numpy as jnp

    params = init_params_deterministic()
    x = deterministic_input(batch=1)
    fwd = build_sharded_forward(BLOCKS12, n_shards=4)

    def loss(p):
        return jnp.sum(fwd(p, x) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in leaves)

"""Precision subsystem tier-1 tests (docs/PRECISION.md): quantize/dequant
roundtrip bounds, per-channel scale correctness on seeded weights, the
fp32-oracle ToleranceGate (pass on bf16/int8w, fail on injected SDC
perturbations, oracle-preflight fault), the dtype-swept autotuner with an
attributably gate-pruned candidate, policy threading through
configs.build_forward and the sharded pallas builder, and the run CLI
--dtype line.

The dtype sweep uses the injected deterministic timer (same discipline as
tests/test_tuning.py) so the race outcome is scripted; the GATE always
runs the real forwards — its verdicts are the thing under test.
"""

import json
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.configs import REGISTRY, build_forward
from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import Blocks12Config
from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
    init_params_random,
    random_input,
)
from cuda_mpi_gpu_cluster_programming_tpu.precision import (
    DEFAULT_BUDGETS,
    DtypePolicy,
    LayerPrecision,
    StageBudget,
    ToleranceGate,
    dequantize,
    forward_blocks12_int8w,
    quantize_channelwise,
    quantize_conv_params,
    resolve_policy,
)
from cuda_mpi_gpu_cluster_programming_tpu.precision.quantize import (
    QMAX,
    roundtrip_error_bound,
)
from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal
from cuda_mpi_gpu_cluster_programming_tpu.resilience.sentinel import inject_bit_flip
from cuda_mpi_gpu_cluster_programming_tpu.tuning import plan as tp
from cuda_mpi_gpu_cluster_programming_tpu.tuning.autotune import (
    DTYPES,
    autotune_precision,
)

SMALL = Blocks12Config(in_height=43, in_width=43)


@pytest.fixture(scope="module")
def seeded():
    """Params + input from the seeded init stream — the same calibration
    source the production sweep gates on."""
    kp, kx = jax.random.split(jax.random.PRNGKey(0))
    return init_params_random(kp, SMALL), random_input(kx, 2, SMALL)


def scripted_timer(g, v, dtype, batch, repeats, warmup):
    """Deterministic dtype race: bf16 < int8w < fp32."""
    return {"fp32": 5.0, "bf16": 1.0, "int8w": 2.0}[dtype], 0.01, 3


# ------------------------------------------------------------- quantize ---


def test_quantize_roundtrip_error_bound(seeded):
    """Roundtrip error of every seeded conv weight is elementwise within
    scale/2 — the bound the scheme promises (docs/PRECISION.md)."""
    params, _x = seeded
    for name in ("conv1", "conv2"):
        w = params[name]["w"]
        q, scale = quantize_channelwise(w)
        assert q.dtype == np.int8
        assert int(np.max(np.abs(np.asarray(q, np.int32)))) <= QMAX
        err = np.abs(np.asarray(dequantize(q, scale)) - np.asarray(w))
        bound = np.asarray(roundtrip_error_bound(w))
        assert np.all(err <= bound + 1e-7), name


def test_per_channel_scale_correctness():
    """scale[k] == max|w[..., k]|/127 per output channel; an all-zero
    channel takes scale 1.0 (safe divide) and quantizes to exact zeros."""
    w = np.zeros((3, 3, 2, 4), np.float32)
    w[..., 0] = 0.5
    w[..., 1] = -2.0
    w[0, 0, 0, 2] = 127.0
    # channel 3 stays all-zero
    q, scale = quantize_channelwise(w)
    np.testing.assert_allclose(
        np.asarray(scale), [0.5 / QMAX, 2.0 / QMAX, 1.0, 1.0], rtol=1e-6
    )
    q = np.asarray(q, np.int32)
    assert np.all(q[..., 0] == QMAX) and np.all(q[..., 1] == -QMAX)
    assert q[0, 0, 0, 2] == QMAX and np.all(q[..., 3] == 0)


def test_quantize_conv_params_tree_shape(seeded):
    """Both conv layers quantized; biases stay fp32 (added after the
    rescale, in the accumulation dtype)."""
    params, _x = seeded
    qp = quantize_conv_params(params)
    assert set(qp) == {"conv1", "conv2"}
    for name, e in qp.items():
        assert e["q"].dtype == np.int8
        assert e["scale"].dtype == np.float32
        assert e["scale"].shape == (params[name]["w"].shape[-1],)
        assert e["b"].dtype == np.float32


def test_int8w_forward_tiers_agree(seeded):
    """The quantized forward's two op tiers (reference conv vs Pallas
    kernels) compute the same function."""
    params, x = seeded
    ref = np.asarray(forward_blocks12_int8w(params, x, SMALL, tier="reference"))
    pal = np.asarray(forward_blocks12_int8w(params, x, SMALL, tier="pallas"))
    np.testing.assert_allclose(pal, ref, rtol=2e-2, atol=2e-2)


# ----------------------------------------------------------------- gate ---


def test_gate_passes_bf16_and_int8w_on_blocks12(seeded, tmp_path):
    """bf16 and int8w both clear their default budgets against the fp32
    oracle on Blocks 1-2 seeded weights, with positive margin, and every
    screening lands one journaled verdict."""
    params, x = seeded
    journal = Journal(tmp_path / "gate.jsonl")
    gate = ToleranceGate(journal=journal)
    for pol in ("bf16", "int8w"):
        res = gate.screen(pol, params, x, SMALL)
        assert res.passed and res.margin > 0.0, (pol, res.reason())
        assert {s.stage for s in res.stages} == {
            "conv1", "pool1", "conv2", "pool2", "lrn2"
        }
    recs = Journal.load(tmp_path / "gate.jsonl")
    assert [r["kind"] for r in recs] == ["gate_pass", "gate_pass"]
    assert all(r["margin"] > 0 for r in recs)


def test_gate_fails_on_injected_perturbation(seeded, tmp_path):
    """A bit-flipped candidate param tree (the chaos ``sdc`` payload,
    resilience.sentinel.inject_bit_flip) gated against the CLEAN oracle
    must fail with an attributable per-stage reason."""
    params, x = seeded
    corrupted, where = inject_bit_flip(params, seed=1)
    assert where is not None
    journal = Journal(tmp_path / "gate.jsonl")
    gate = ToleranceGate(journal=journal)
    res = gate.screen("bf16", params, x, SMALL, candidate_params=corrupted)
    assert not res.passed and res.margin < 0.0
    assert res.worst_stage in {"conv1", "pool1", "conv2", "pool2", "lrn2"}
    assert "stage" in res.reason() and "budget" in res.reason()
    (rec,) = Journal.load(tmp_path / "gate.jsonl")
    assert rec["kind"] == "gate_fail" and rec["reason"] == res.reason()


def test_gate_oracle_preflight_fault(seeded, monkeypatch):
    """A device whose fp32 path itself deviates from the numpy loop oracle
    fails EVERY candidate rather than blessing a matching error."""
    from cuda_mpi_gpu_cluster_programming_tpu.resilience import sentinel

    params, x = seeded
    monkeypatch.setattr(sentinel, "oracle_spot_check", lambda *a, **k: 1.0)
    res = ToleranceGate().screen("bf16", params, x, SMALL)
    assert not res.passed and res.oracle_fault
    assert "oracle" in res.reason()


def test_gate_budget_tables_and_margins():
    """Budget lookup: exact stage beats "*"; margin is the binding
    fraction of budget left."""
    gate = ToleranceGate(
        budgets={"bf16": {"*": StageBudget(max_rel=1e-2),
                          "lrn2": StageBudget(max_rel=4e-2)}},
        preflight=False,
    )
    assert gate.budget_for("bf16", "conv1").max_rel == 1e-2
    assert gate.budget_for("bf16", "lrn2").max_rel == 4e-2
    assert DEFAULT_BUDGETS["bf16"]["*"].max_rel < DEFAULT_BUDGETS["int8w"]["*"].max_rel


# --------------------------------------------------------------- policy ---


def test_policy_presets_and_resolution():
    for name in ("fp32", "bf16", "int8w"):
        pol = resolve_policy(name)
        assert pol.name == name
    assert resolve_policy(None).name == "fp32"
    assert resolve_policy("int8w").quantized
    assert not resolve_policy("bf16").quantized
    with pytest.raises(ValueError, match="unknown precision policy"):
        resolve_policy("fp8")
    # Per-layer overrides: named layers diverge from the default triple.
    pol = DtypePolicy(
        "mixed",
        LayerPrecision("bfloat16", "float32", "bfloat16"),
        layers=(("conv1", LayerPrecision("float32", "float32", "float32")),),
    )
    assert pol.layer("conv1").compute == "float32"
    assert pol.layer("conv2").compute == "bfloat16"


def test_build_forward_policy_matches_oracle_within_budget(seeded):
    """The acceptance contract: build_forward(policy=...) reproduces the
    fp32 oracle within the same budget the gate screens that policy at."""
    params, x = seeded
    oracle = np.asarray(build_forward(REGISTRY["v1_jit"], SMALL)(params, x))
    denom = float(np.max(np.abs(oracle)))
    for pol in ("bf16", "int8w"):
        got = np.asarray(
            build_forward(REGISTRY["v1_jit"], SMALL, policy=pol)(params, x)
        )
        rel = float(np.max(np.abs(got - oracle))) / denom
        assert rel <= DEFAULT_BUDGETS[pol]["*"].max_rel, (pol, rel)


def test_build_forward_rejects_quantized_unsupported_configs():
    """ISSUE 17 lifted the halo/replicated int8w refusal, but the
    still-unsupported combos (tensor-parallel, full AlexNet) must keep
    refusing loudly and attributably, not silently run unquantized."""
    for key in ("v7_tp", "v6_full_sharded"):
        with pytest.raises(ValueError, match="open ROADMAP items"):
            build_forward(REGISTRY[key], SMALL, n_shards=2, policy="int8w")
    with pytest.raises(ValueError, match="unknown compute mode"):
        build_forward(REGISTRY["v1_jit"], SMALL, policy="int9")


# ---------------------------------------------------------- dtype sweep ---


def test_autotune_precision_prunes_gate_failed_attributably(seeded, tmp_path):
    """ONE sweep covers {fp32, bf16, int8w}: a zero-budget int8w gate
    prunes that dtype with an attributable journaled reason before any
    timing, the scripted-fastest bf16 wins, the fp32 floor is kept, and
    the winner's policy record persists with its gate_pass verdict."""
    path = tmp_path / "plan.json"
    journal_path = tmp_path / "gate.jsonl"
    gate = ToleranceGate(
        budgets={"int8w": {"*": StageBudget(max_rel=0.0)}},
        journal=Journal(journal_path),
    )
    res = autotune_precision(
        path, SMALL, batch=2, timer=scripted_timer, log=lambda s: None,
        device_kind="cpu", gate=gate, seed=0,
    )
    assert res.winner == "bf16" and not res.cached
    assert set(res.pruned) == {"int8w"}
    assert "stage" in res.pruned["int8w"] and "budget" in res.pruned["int8w"]
    # fp32 reference floor swept and kept alongside the winner.
    assert set(res.plans) == {"fp32", "bf16"}
    assert res.plan is res.plans["bf16"]
    assert "bf16" in res.summary() and "int8w=gate-pruned" in res.summary()
    # Journal: one verdict per screened dtype; the non-fp32 winner exists
    # only with a gate_pass record (the acceptance invariant).
    kinds = {r["policy"]: r["kind"] for r in Journal.load(journal_path)}
    assert kinds == {
        "fp32": "gate_pass", "bf16": "gate_pass", "int8w": "gate_fail"
    }
    # Persisted policy record round-trips with the pruned reasons + gates.
    rec = tp.load_policy(
        path, device_kind="cpu", model_cfg=SMALL, batch=2,
        match_any_batch=False,
    )
    assert rec is not None and rec["dtype"] == "bf16"
    assert sorted(rec["swept"]) == sorted(DTYPES)
    assert rec["pruned"]["int8w"] == res.pruned["int8w"]
    assert rec["gates"]["bf16"]["passed"] and not rec["gates"]["int8w"]["passed"]
    # Per-dtype plans landed under their own keys in the same file.
    obj = json.loads(path.read_text())
    plan_dtypes = {k.split("|")[3] for k in obj["plans"]}
    assert plan_dtypes == {"fp32", "bf16"}


def test_autotune_precision_cache_short_circuits(seeded, tmp_path):
    """A fresh policy record + per-dtype plans short-circuit gate and
    sweep alike; --tune-force re-runs both."""
    path = tmp_path / "plan.json"
    kw = dict(
        batch=2, timer=scripted_timer, log=lambda s: None, device_kind="cpu",
        gate=ToleranceGate(), seed=0,
    )
    first = autotune_precision(path, SMALL, **kw)
    assert not first.cached
    calls = []

    def counting_timer(*a):
        calls.append(a)
        return scripted_timer(*a)

    second = autotune_precision(path, SMALL, **{**kw, "timer": counting_timer})
    assert second.cached and not calls
    assert second.winner == first.winner
    assert second.plan.plan_hash() == first.plan.plan_hash()
    forced = autotune_precision(
        path, SMALL, force=True, **{**kw, "timer": counting_timer}
    )
    assert not forced.cached and calls


def test_autotune_precision_all_pruned_raises(seeded, tmp_path):
    """Every dtype gate-pruned (broken oracle chain) is a loud error
    carrying each dtype's reason — never a silent default plan."""
    gate = ToleranceGate(
        budgets={
            name: {"*": StageBudget(max_abs=-1.0)} for name in ("fp32", "bf16")
        },
    )
    with pytest.raises(RuntimeError, match="gate-pruned"):
        autotune_precision(
            tmp_path / "plan.json", SMALL, batch=2, dtypes=("fp32", "bf16"),
            timer=scripted_timer, log=lambda s: None, device_kind="cpu",
            gate=gate, seed=0,
        )


def test_int8w_candidate_space_excludes_epilogue_fusion():
    """hpool fusion needs the in-kernel bias/ReLU epilogue; int8w's rescale
    lands between accumulation and bias, so the sweep must not offer it.
    Block fusion (the ISSUE 17 megakernel) IS legal under int8w — its
    epilogue rescales the fp32 accumulator before bias by construction —
    so "block" stays in the quantized space."""
    from cuda_mpi_gpu_cluster_programming_tpu.tuning import space as ts

    for g in ts.conv_geometries(SMALL):
        fp32_fuses = {v.fuse for v in ts.candidate_space(g, interpret=True)}
        int8_fuses = {
            v.fuse
            for v in ts.candidate_space(g, interpret=True, dtype="int8w")
        }
        assert "hpool" in fp32_fuses
        assert "block" in fp32_fuses
        assert "hpool" not in int8_fuses
        assert int8_fuses == {"none", "block"}


# ------------------------------------------------------------- threading ---


def test_sharded_pallas_builder_applies_plan(seeded):
    """PR 5 leftover closed: a TunePlan rides into the SHARDED pallas
    builder and reproduces the untuned output (allclose across lowering
    variants, same contract as the single-device threading test)."""
    from cuda_mpi_gpu_cluster_programming_tpu.parallel.sharded import (
        build_sharded_forward,
    )
    from cuda_mpi_gpu_cluster_programming_tpu.tuning.autotune import autotune_model

    params, x = seeded
    plan = autotune_model(
        SMALL, dtype="fp32", batch=2,
        timer=lambda g, v, *a: (1.0 if v.conv == "taps" else 5.0, 0.01, 3),
        log=lambda s: None, device_kind="cpu",
    )
    assert all(v.conv == "taps" for _n, v in plan.layers)
    base = np.asarray(build_sharded_forward(SMALL, 2, tier="pallas")(params, x))
    tuned = np.asarray(
        build_sharded_forward(SMALL, 2, tier="pallas", plan=plan)(params, x)
    )
    np.testing.assert_allclose(tuned, base, rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------------- CLI ---


def test_run_dtype_cli_line():
    """run.py --dtype pins the policy and prints the machine-parsed
    Precision line (harness._RE_PRECISION)."""
    root = Path(__file__).resolve().parent.parent
    r = subprocess.run(
        [
            sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
            "--config", "v1_jit", "--batch", "1", "--height", "35",
            "--width", "35", "--repeats", "1", "--warmup", "1",
            "--dtype", "int8w",
        ],
        capture_output=True, text=True, timeout=300, cwd=root,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Precision: dtype=int8w source=dtype gate=none" in r.stdout
    from cuda_mpi_gpu_cluster_programming_tpu.harness import _RE_PRECISION

    m = _RE_PRECISION.search(r.stdout)
    assert m and m.group(1) == "int8w"


def test_run_dtype_policy_mutually_exclusive():
    root = Path(__file__).resolve().parent.parent
    r = subprocess.run(
        [
            sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
            "--dtype", "bf16", "--policy", "int8w",
        ],
        capture_output=True, text=True, timeout=120, cwd=root,
    )
    assert r.returncode == 2
    assert "mutually exclusive" in r.stderr

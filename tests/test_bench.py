"""bench.py contract tests: one parseable JSON line in every regime, and the
wedge-fallback schema a driver parses when the tunnel is down.

The reference's equivalent contract is the ``... completed in X ms`` stdout
line its harness regex consumes (scripts/common_test_utils.sh:296-297); here
the contract is a single JSON object whose schema must stay stable for the
round driver (BENCH_r0N.json) and the warehouse.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402


def test_error_json_surfaces_last_good_without_confusable_value():
    """Wedge fallback: top-level value stays 0.0, value_last_good carries the
    committed headline, and last_good has no plain 'value' field a scanner
    could mistake for fresh (round-3 verdict item 8 + advisor finding)."""
    with open(os.path.join(ROOT, "perf", "bench_latest.json")) as f:
        committed = json.load(f)
    out = json.loads(bench._error_json("device wedged (test)"))
    assert out["value"] == 0.0
    assert out["error"] == "device wedged (test)"
    assert out["value_last_good"] == committed["value"] > 0
    assert out["last_good"]["stale"] is True
    assert out["last_good"]["stale_value"] == committed["value"]
    assert "value" not in out["last_good"]


def test_error_json_survives_missing_last_good(tmp_path, monkeypatch):
    """No committed headline -> still one parseable JSON line, no last_good."""
    fake_root = tmp_path / "repo"
    (fake_root / "perf").mkdir(parents=True)
    monkeypatch.setattr(bench, "ROOT", str(fake_root))
    out = json.loads(bench._error_json("down"))
    assert out["value"] == 0.0
    assert "last_good" not in out and "value_last_good" not in out


def test_error_json_stale_rename_recurses_into_bf16(tmp_path, monkeypatch):
    """Once bench_latest carries the bf16 sub-object, its nested 'value' must
    be renamed too — no fresh-looking numeric survives anywhere in last_good."""
    fake_root = tmp_path / "repo"
    (fake_root / "perf").mkdir(parents=True)
    (fake_root / "perf" / "bench_latest.json").write_text(json.dumps(
        {"value": 21000.0, "unit": "img/s", "bf16": {"value": 140000.0, "mfu": 0.86}}
    ))
    monkeypatch.setattr(bench, "ROOT", str(fake_root))
    out = json.loads(bench._error_json("down"))
    assert out["value_last_good"] == 21000.0
    assert out["last_good"]["stale_value"] == 21000.0
    assert out["last_good"]["bf16"]["stale_value"] == 140000.0
    assert "value" not in out["last_good"]
    assert "value" not in out["last_good"]["bf16"]


def test_error_json_flags_last_good_config_mismatch(tmp_path, monkeypatch):
    """Round-4 verdict item 8: a last_good captured under different
    (config, compute, batch) than the current defaults must be flagged
    machine-readably, with the delta spelled out."""
    fake_root = tmp_path / "repo"
    (fake_root / "perf").mkdir(parents=True)
    (fake_root / "perf" / "bench_latest.json").write_text(json.dumps(
        {"value": 23492.4, "unit": "img/s", "config": bench.CONFIG,
         "compute": bench.COMPUTE, "batch": bench.BATCH + 128}
    ))
    monkeypatch.setattr(bench, "ROOT", str(fake_root))
    out = json.loads(bench._error_json("down"))
    assert out["last_good_config_mismatch"] is True
    assert out["last_good_config_delta"] == {
        "batch": {"last_good": bench.BATCH + 128, "current": bench.BATCH}
    }


def test_error_json_no_mismatch_flag_when_configs_match(tmp_path, monkeypatch):
    """Matching capture conditions -> no mismatch fields at all (absence is
    the machine-readable all-clear)."""
    fake_root = tmp_path / "repo"
    (fake_root / "perf").mkdir(parents=True)
    (fake_root / "perf" / "bench_latest.json").write_text(json.dumps(
        {"value": 23492.4, "unit": "img/s", "config": bench.CONFIG,
         "compute": bench.COMPUTE, "batch": bench.BATCH}
    ))
    monkeypatch.setattr(bench, "ROOT", str(fake_root))
    out = json.loads(bench._error_json("down"))
    assert "last_good_config_mismatch" not in out
    assert "last_good_config_delta" not in out


def test_default_batch_is_round_comparable():
    """Advisor (round 3): the default-batch headline must stay comparable
    round-over-round; 256 is opt-in via BENCH_BATCH."""
    assert bench.BATCH == 128 or os.environ.get("BENCH_BATCH")


def test_bench_end_to_end_cpu_schema():
    """Full bench.py subprocess on the CPU backend: asserts the fresh-run
    schema, including the bf16 sub-object and the n/CI timing fields."""
    env = dict(os.environ)
    env.update(
        # BOTH are required to keep subprocesses off the tunneled chip: with
        # only JAX_PLATFORMS=cpu the axon sitecustomize still contacts the
        # pool at startup and inherits a wedge (observed round 3/4).
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        BENCH_BATCH="4",
        BENCH_REPEATS="3",
        BENCH_PROBE_TIMEOUT="120",
        BENCH_TIMEOUT="600",
    )
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=env,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    line = next(l for l in reversed(res.stdout.splitlines()) if l.startswith("{"))
    out = json.loads(line)
    assert out["metric"] == bench.METRIC
    assert out["value"] > 0
    assert out["batch"] == 4
    assert out["timing_n"] >= 1 and out["timing_ci95_ms"] >= 0.0
    assert out["timing_shadowed"] in (True, False)
    assert out["timing_underconverged"] in (True, False)
    # CPU: no peak table entry, so MFU fields are null and bf16 is skipped
    # (the sub-object is a TPU-capability statement).
    assert out["mfu"] is None
    assert "bf16" not in out
    # ISSUE 9: measure rows carry the per-stage breakdown at the sentinel
    # tap boundaries, and the stage sum holds the sums-to-total contract
    # against the independently measured per_pass_ms (15% CPU-mesh budget).
    bd = out["breakdown"]
    assert set(bd["stages"]) == {"conv1", "pool1", "conv2", "pool2", "lrn2"}
    assert all(ms >= 0 for ms in bd["stages"].values())
    assert bd["stage_sum_ms"] == pytest.approx(out["per_pass_ms"], rel=0.15)
    assert bd["method"] == "prefix-diff" and bd["batch"] == 4
    # ISSUE 13: the roofline join rides beside the breakdown — per-stage
    # bound verdicts ranked by headroom, the fused-block ceiling, and the
    # assumed-spec marker on CPU (no real roof to judge against).
    rf = out["roofline"]
    assert rf["source"] == "breakdown" and rf["spec_assumed"] is True
    assert {s["name"] for s in rf["stages"]} == set(bd["stages"])
    assert all(s["bound"] in ("compute", "memory") for s in rf["stages"])
    assert [s["headroom_ms"] for s in rf["stages"]] == sorted(
        [s["headroom_ms"] for s in rf["stages"]], reverse=True
    )
    assert set(rf["blocks"]) == {"block1", "block2"}
    assert 0 < rf["blocks"]["block2"]["fused_mfu_ceiling"] <= 1.0


def test_bench_multi_config_sweep_one_row_per_config():
    """BENCH_CONFIGS: one parseable JSON row PER config (the V1->V5 story
    measured), each with the standard schema and its own config key."""
    env = dict(os.environ)
    env.update(
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        BENCH_CONFIGS="v1_jit,v3_pallas",
        BENCH_BATCH="2",
        BENCH_REPEATS="2",
        BENCH_TIMEOUT="600",
    )
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=env,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    rows = [json.loads(l) for l in res.stdout.splitlines() if l.startswith("{")]
    assert [r["config"] for r in rows] == ["v1_jit", "v3_pallas"]
    for r in rows:
        assert r["metric"] == bench.METRIC
        assert r["value"] > 0 and r["batch"] == 2
        assert r["timing_n"] >= 1
    # ISSUE 9: the reference tier attributes for real; the Pallas tier on
    # CPU degrades to a visible note (interpret-mode staging would
    # attribute tracing overhead, not kernels).
    assert rows[0]["breakdown"]["stage_sum_ms"] > 0
    assert "skipped" in rows[1]["breakdown"]


def test_error_rows_carry_their_config(tmp_path, monkeypatch):
    """Multi-config error paths label every row; _error_obj defaults to the
    single-config contract otherwise."""
    fake_root = tmp_path / "repo"
    (fake_root / "perf").mkdir(parents=True)
    monkeypatch.setattr(bench, "ROOT", str(fake_root))
    assert json.loads(bench._error_json("down"))["config"] == bench.CONFIG
    assert bench._error_obj("down", config="v3_pallas")["config"] == "v3_pallas"


def _good_row(config):
    return {
        "metric": bench.METRIC, "value": 50.0, "unit": "img/s",
        "vs_baseline": 9.2, "platform": "cpu", "config": config, "batch": 2,
    }


def test_bench_journal_resume_restarts_at_first_missing_config(tmp_path, monkeypatch, capsys):
    """BENCH_JOURNAL: a sweep killed after measuring config A relaunches and
    measures ONLY the missing config B, replaying A's journaled row."""
    journal = tmp_path / "bench_journal.jsonl"
    monkeypatch.setenv("BENCH_JOURNAL", str(journal))
    monkeypatch.setenv("BENCH_MAX_RETRIES", "0")
    monkeypatch.setattr(bench, "CONFIGS", ["v1_jit", "v3_pallas"])
    asked = []

    def fake_measure(configs=None):
        asked.append(list(configs))
        # First invocation: A measures, then the process "dies" before B
        # (B yields an error row, as the salvage path reports).
        rows = []
        for c in configs:
            if c == "v3_pallas" and len(asked) == 1:
                rows.append(bench._error_obj("child died before v3_pallas", "cpu", c))
            else:
                rows.append(_good_row(c))
        return rows

    monkeypatch.setattr(bench, "_measure_once", fake_measure)
    assert bench.main() == 0
    out1 = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert [r["config"] for r in out1] == ["v1_jit", "v3_pallas"]
    assert out1[0]["value"] > 0 and out1[1].get("error")
    assert asked == [["v1_jit", "v3_pallas"]]

    # Relaunch: only the missing config is measured; A replays from the
    # journal with its originally measured value (modulo attempt metadata).
    assert bench.main() == 0
    out2 = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert asked[1] == ["v3_pallas"]
    assert [r["config"] for r in out2] == ["v1_jit", "v3_pallas"]
    assert out2[0]["value"] == out1[0]["value"]
    assert out2[1]["value"] > 0 and "error" not in out2[1]

    # Third launch: everything journaled — nothing measured at all.
    assert bench.main() == 0
    out3 = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert len(asked) == 2
    assert [r["config"] for r in out3] == ["v1_jit", "v3_pallas"]


def test_bench_no_journal_keeps_historical_contract(monkeypatch, capsys):
    """Without BENCH_JOURNAL nothing is journaled and every config is
    measured every run (the historical contract)."""
    monkeypatch.delenv("BENCH_JOURNAL", raising=False)
    monkeypatch.setenv("BENCH_MAX_RETRIES", "0")
    monkeypatch.setattr(bench, "CONFIGS", ["v1_jit"])
    asked = []

    def fake_measure(configs=None):
        asked.append(list(configs))
        return [_good_row(c) for c in configs]

    monkeypatch.setattr(bench, "_measure_once", fake_measure)
    assert bench.main() == 0
    assert bench.main() == 0
    assert asked == [["v1_jit"], ["v1_jit"]]
    rows = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert all(r["attempts"] == 1 for r in rows)


def test_bench_journal_never_journals_wedged_rows(tmp_path, monkeypatch, capsys):
    """A wedged/error row must NOT be journaled — replaying a value=0.0 row
    on resume would recommit the exact garbage the retry loop exists to
    refuse."""
    journal = tmp_path / "bench_journal.jsonl"
    monkeypatch.setenv("BENCH_JOURNAL", str(journal))
    monkeypatch.setenv("BENCH_MAX_RETRIES", "0")
    monkeypatch.setattr(bench, "CONFIGS", ["v1_jit"])
    monkeypatch.setattr(
        bench, "_measure_once",
        lambda configs=None: [bench._error_obj("wedged", "cpu", c) for c in configs],
    )
    assert bench.main() == 0
    capsys.readouterr()
    from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal

    assert Journal.completed(Journal.load(journal), "bench_row") == {}

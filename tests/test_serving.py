"""Continuous-batching serving subsystem tests — CPU, virtual 8-device mesh.

Covers the tentpole surface (docs/SERVING.md): admission-queue FIFO +
backpressure, the bucket-assembly invariants (every dispatched batch's
padded size is a member of the configured bucket set; no request is ever
lost or reordered), explicit deadline shedding (SHED status + journal
record, never a silent drop), the TunePlan-derived bucket set, the
zero-cache-miss dispatch discipline, the seeded ``device_loss`` chaos
drill (in-flight requests finish via supervisor replay, bit-identical to
an unfaulted run pinned to the degraded rung), the Poisson load generator,
and the two CLI surfaces: ``run --serve`` and the ``bench.py`` serve mode
(the tier-1 CPU-mesh serve smoke).
"""

import dataclasses
import json
import os
import random
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from cuda_mpi_gpu_cluster_programming_tpu.models.alexnet import (
    BLOCKS12,
    forward_blocks12,
)
from cuda_mpi_gpu_cluster_programming_tpu.models.init import (
    init_params_deterministic,
)
from cuda_mpi_gpu_cluster_programming_tpu.resilience import chaos
from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal
from cuda_mpi_gpu_cluster_programming_tpu.serving.batcher import (
    Batcher,
    bucket_for,
    power_of_two_buckets,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.loadgen import (
    percentile,
    poisson_arrivals,
    run_load,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.queue import (
    OK,
    SHED,
    AdmissionQueue,
    QueueFull,
)
from cuda_mpi_gpu_cluster_programming_tpu.serving.server import (
    InferenceServer,
    ServeConfig,
    request_latencies_from_journal,
)

ROOT = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(BLOCKS12, in_height=63, in_width=63)


def _img(v: float = 1.0, n: int = 1) -> np.ndarray:
    return np.full((n, CFG.in_height, CFG.in_width, CFG.in_channels), v, np.float32)


@pytest.fixture(autouse=True)
def _chaos_off(monkeypatch):
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.reset()
    yield
    chaos.reset()


# ------------------------------------------------------------- buckets ---


def test_power_of_two_buckets():
    assert power_of_two_buckets(1) == (1,)
    assert power_of_two_buckets(8) == (1, 2, 4, 8)
    # a non-power-of-two ceiling is itself a legal dispatch shape
    assert power_of_two_buckets(6) == (1, 2, 4, 6)
    with pytest.raises(ValueError):
        power_of_two_buckets(0)


def test_bucket_for_picks_smallest_fit_and_rejects_oversize():
    assert bucket_for(1, (1, 2, 4)) == 1
    assert bucket_for(3, (1, 2, 4)) == 4
    with pytest.raises(ValueError, match="fit no bucket"):
        bucket_for(5, (1, 2, 4))


# --------------------------------------------------------------- queue ---


def test_queue_fifo_order_and_backpressure():
    q = AdmissionQueue(max_pending=2)
    h1 = q.submit(_img(1.0))
    h2 = q.submit(_img(2.0))
    with pytest.raises(QueueFull):
        q.submit(_img(3.0))
    taken, shed = q.pop_ready(max_images=8)
    assert [r.handle for r in taken] == [h1, h2] and shed == []
    assert len(q) == 0


def test_pop_ready_sheds_expired_explicitly():
    q = AdmissionQueue()
    expired = q.submit(_img(1.0), deadline_s=1e-9)
    live = q.submit(_img(2.0))
    import time

    time.sleep(0.01)
    taken, shed = q.pop_ready(max_images=8)
    # the expired request is returned for journaling AND its handle is
    # completed SHED — counted, attributed, never silently dropped
    assert [r.handle for r in shed] == [expired]
    assert expired.status == SHED and "deadline" in expired.error
    assert [r.handle for r in taken] == [live]


def test_queue_rejects_bad_rank():
    q = AdmissionQueue()
    with pytest.raises(ValueError, match="request input"):
        q.submit(np.zeros((4, 4)))


# ------------------------------------------------------------- batcher ---


def test_batch_assembly_invariants_random_streams():
    """THE bucket invariant: over seeded random request streams, every
    assembled batch's padded size is in the bucket set, requests stay in
    FIFO order, and each request lands in exactly one batch."""
    rng = random.Random(7)
    for trial in range(5):
        q = AdmissionQueue()
        buckets = power_of_two_buckets(rng.choice([4, 8, 6]))
        batcher = Batcher(q, buckets)
        handles = [
            q.submit(_img(float(i), n=rng.randint(1, buckets[-1])))
            for i in range(rng.randint(3, 12))
        ]
        seen = []
        while len(q):
            batch, shed = batcher.next_batch(wait_s=0.0)
            assert shed == []
            assert batch is not None
            assert batch.bucket in buckets  # the invariant
            assert batch.n_images <= batch.bucket
            assert batch.padded_input().shape[0] == batch.bucket
            seen.extend(r.handle for r in batch.requests)
        assert seen == handles  # FIFO, nothing lost, nothing duplicated


def test_padded_input_zero_pads_after_payload():
    q = AdmissionQueue()
    q.submit(_img(3.0, n=3))
    batch, _ = Batcher(q, (1, 2, 4)).next_batch(wait_s=0.0)
    xb = batch.padded_input()
    assert xb.shape[0] == 4 and batch.pad == 1
    assert (xb[:3] == 3.0).all() and (xb[3:] == 0.0).all()


# ------------------------------------------------------------- loadgen ---


def test_poisson_arrivals_deterministic_and_bounded():
    a = poisson_arrivals(100.0, 1.0, seed=3)
    b = poisson_arrivals(100.0, 1.0, seed=3)
    assert a == b and all(0 < t < 1.0 for t in a)
    assert a == sorted(a)
    assert poisson_arrivals(100.0, 1.0, seed=4) != a
    assert poisson_arrivals(0.0, 1.0) == []
    # law of large numbers sanity: ~rate*duration arrivals
    n = len(poisson_arrivals(200.0, 5.0, seed=0))
    assert 800 < n < 1200


def test_percentile_nearest_rank():
    xs = [10.0, 20.0, 30.0, 40.0]
    assert percentile(xs, 50) == 20.0
    assert percentile(xs, 99) == 40.0
    assert percentile(xs, 0) == 10.0
    assert percentile([], 50) is None
    assert percentile([5.0], 99) == 5.0


# -------------------------------------------------- TunePlan bucket set ---


def test_plan_batches_derives_bucket_set(tmp_path):
    from cuda_mpi_gpu_cluster_programming_tpu.tuning.plan import (
        code_rev,
        plan_batches,
        plan_key,
        shape_key,
    )

    rev = code_rev()
    sk = shape_key(CFG)
    plans = {
        plan_key("cpu", sk, 2, "fp32", rev): {"batch": 2},
        plan_key("cpu", sk, 8, "fp32", rev): {"batch": 8},
        # stale rev: winners describe old kernels — excluded
        plan_key("cpu", sk, 4, "fp32", "deadbeefdead"): {"batch": 4},
        # other dtype/device points — excluded
        plan_key("cpu", sk, 16, "bf16", rev): {"batch": 16},
        plan_key("TPU v5 lite", sk, 32, "fp32", rev): {"batch": 32},
        # malformed entry — skipped, not fatal
        plan_key("cpu", sk, 64, "fp32", rev): {"batch": "nope"},
    }
    path = tmp_path / "plan.json"
    path.write_text(json.dumps({"version": 1, "plans": plans}))
    assert plan_batches(
        path, device_kind="cpu", model_cfg=CFG, dtype="fp32"
    ) == [2, 8]
    assert plan_batches(
        path, device_kind="cpu", model_cfg=CFG, dtype="int8"
    ) == []
    assert plan_batches(
        tmp_path / "missing.json", device_kind="cpu", model_cfg=CFG, dtype="fp32"
    ) == []


def test_server_buckets_from_plan(tmp_path):
    from cuda_mpi_gpu_cluster_programming_tpu.tuning.plan import (
        code_rev,
        plan_key,
        shape_key,
    )

    rev, sk = code_rev(), shape_key(CFG)
    kind = jax.devices()[0].device_kind
    plans = {
        plan_key(kind, sk, b, "fp32", rev): {"batch": b} for b in (2, 4, 16)
    }
    path = tmp_path / "plan.json"
    path.write_text(json.dumps({"version": 1, "plans": plans}))
    srv = InferenceServer(
        ServeConfig(max_batch=8, plan_path=str(path), model_cfg=CFG)
    )
    # tuned batches <= max_batch become the bucket set; 16 is filtered
    assert srv.buckets == (2, 4)
    # no matching plan -> powers-of-two fallback
    srv2 = InferenceServer(
        ServeConfig(max_batch=8, plan_path=str(tmp_path / "none.json"), model_cfg=CFG)
    )
    assert srv2.buckets == (1, 2, 4, 8)


# -------------------------------------------------------------- server ---


def test_serve_roundtrip_matches_reference(tmp_path):
    jpath = tmp_path / "serve.jsonl"
    srv = InferenceServer(
        ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                    journal_path=str(jpath))
    )
    sizes = [1, 3, 2, 1, 4]
    handles = [srv.submit(_img(1.0 + 0.1 * i, n=n)) for i, n in enumerate(sizes)]
    srv.run_until_drained()
    params = init_params_deterministic(CFG)
    fwd = jax.jit(lambda p, x: forward_blocks12(p, x, CFG))
    for i, (h, n) in enumerate(zip(handles, sizes)):
        assert h.status == OK and h.result.shape[0] == n
        want = np.asarray(fwd(params, _img(1.0 + 0.1 * i, n=n)))
        np.testing.assert_allclose(h.result, want, rtol=1e-5, atol=1e-5)
    # zero post-warmup compiles: every dispatched shape was a warmed bucket
    assert srv.stats.cache_misses == 0
    assert srv.stats.warmup_compiles == len(srv.buckets)
    recs = Journal.load(jpath)
    batches = [r for r in recs if r["kind"] == "serve_batch"]
    assert batches and all(r["bucket"] in srv.buckets for r in batches)
    assert sum(r["n_requests"] for r in batches) == len(sizes)
    # journaled per-request latencies cover every completed request
    assert len(request_latencies_from_journal(jpath)) == len(sizes)
    warm = [r for r in recs if r["kind"] == "serve_warm"]
    assert [r["bucket"] for r in warm] == list(srv.buckets)


def test_deadline_shed_is_explicit_and_journaled(tmp_path):
    jpath = tmp_path / "serve.jsonl"
    srv = InferenceServer(
        ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                    journal_path=str(jpath))
    )
    import time

    doomed = [srv.submit(_img(), deadline_s=1e-9) for _ in range(3)]
    live = [srv.submit(_img()) for _ in range(2)]
    time.sleep(0.01)
    srv.run_until_drained()
    assert all(h.status == SHED for h in doomed)
    assert all(h.status == OK for h in live)
    # accounting closes: every submitted request is ok or shed, and the
    # journal carries one serve_shed record per shed request
    assert srv.stats.n_ok + srv.stats.n_shed == len(doomed) + len(live)
    recs = Journal.load(jpath)
    assert len([r for r in recs if r["kind"] == "serve_shed"]) == len(doomed)


def test_submit_rejects_wider_than_largest_bucket():
    srv = InferenceServer(ServeConfig(max_batch=4, model_cfg=CFG))
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        srv.submit(_img(n=5))


def test_chaos_device_loss_drill_replays_in_flight_bit_identical(
    tmp_path, monkeypatch
):
    """The acceptance drill through the serving stack: a device loss mid-
    load trips the supervisor, the service re-plans down the ladder,
    re-warms every bucket on the new rung, REPLAYS the in-flight batch,
    and every request finishes with outputs bit-identical to an unfaulted
    server pinned to the degraded rung. Zero cache misses throughout."""
    jpath = tmp_path / "serve.jsonl"
    scfg = ServeConfig(config="v2.2_sharded", n_shards=2, max_batch=4,
                       supervise=True, model_cfg=CFG, journal_path=str(jpath))
    imgs = [_img(1.0 + 0.01 * i) for i in range(6)]

    monkeypatch.setenv(chaos.CHAOS_ENV, "seed=3,device_loss=1")
    chaos.reset()
    faulted = InferenceServer(scfg)
    handles = [faulted.submit(im) for im in imgs]
    faulted.run_until_drained()
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()

    assert all(h.status == OK for h in handles)  # nobody 500s
    assert [t.kind for t in faulted.sup.trips] == ["device_loss"]
    assert faulted.sup.entry.key == "replicated@2:reference"
    assert faulted.stats.cache_misses == 0  # re-warm keeps the discipline
    kinds = [r["kind"] for r in Journal.load(jpath)]
    assert "sup_trip" in kinds and "serve_rewarm" in kinds
    assert kinds.index("serve_rewarm") < kinds.index("serve_batch")

    clean = InferenceServer(
        dataclasses.replace(scfg, journal_path=""),
        ladder=[faulted.sup.entry],
    )
    clean_handles = [clean.submit(im) for im in imgs]
    clean.run_until_drained()
    for a, b in zip(handles, clean_handles):
        assert b.status == OK
        assert np.array_equal(a.result, b.result)


def test_chaos_mesh_shrink_drill_server_survives_with_zero_misses(
    tmp_path, monkeypatch
):
    """ISSUE 8 serving drill: a seeded mesh_shrink ACTUALLY drops devices
    mid-load; the supervisor rebuilds the rung over the survivors,
    live-reshards the params, re-warms every bucket, and replays — the
    server finishes with completed == n_requests and ZERO post-rewarm
    cache misses, bit-identical to a clean server pinned to the landed
    rung."""
    jpath = tmp_path / "serve.jsonl"
    scfg = ServeConfig(config="v2.2_sharded", n_shards=4, max_batch=4,
                       supervise=True, model_cfg=CFG, journal_path=str(jpath))
    imgs = [_img(1.0 + 0.01 * i) for i in range(6)]

    monkeypatch.setenv(chaos.CHAOS_ENV, "seed=3,mesh_shrink=2")
    chaos.reset()
    shrunk = InferenceServer(scfg)
    handles = [shrunk.submit(im) for im in imgs]
    shrunk.run_until_drained()
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()

    assert sum(1 for h in handles if h.status == OK) == len(imgs)
    assert [t.kind for t in shrunk.sup.trips] == ["mesh_shrink"]
    assert shrunk.sup.pool.n_total == 8 and shrunk.sup.pool.n_alive == 6
    assert shrunk.sup.entry.key == "halo@2:reference"  # the surviving rung
    assert shrunk.stats.cache_misses == 0  # zero post-rewarm misses
    assert shrunk.stats.rewarm_ms > 0
    kinds = [r["kind"] for r in Journal.load(jpath)]
    assert "mesh_shrink" in kinds  # the pool's shrink record
    assert kinds.index("serve_rewarm") < kinds.index("serve_batch")

    clean = InferenceServer(
        dataclasses.replace(scfg, journal_path=""),
        ladder=[shrunk.sup.entry],
    )
    clean_handles = [clean.submit(im) for im in imgs]
    clean.run_until_drained()
    for a, b in zip(handles, clean_handles):
        assert b.status == OK
        assert np.array_equal(a.result, b.result)


def test_grow_back_drill_promotes_with_zero_misses_bit_identical(
    tmp_path, monkeypatch
):
    """ISSUE 10 serving drill: a seeded mesh shrink degrades the service;
    healing the lost device puts it in probation; after N clean batches it
    graduates and the dispatch loop PROMOTES back to the original rung
    between batches — completed == offered end to end, ZERO cache misses
    (every bucket re-warmed at the higher rung before cutover), and every
    wave's outputs bit-identical to a clean server pinned to that wave's
    topology."""
    jpath = tmp_path / "serve.jsonl"
    scfg = ServeConfig(config="v2.2_sharded", n_shards=4, max_batch=4,
                       supervise=True, model_cfg=CFG, journal_path=str(jpath))
    imgs = [_img(1.0 + 0.01 * i) for i in range(6)]

    def _wave(server):
        handles = [server.submit(im) for im in imgs]
        server.run_until_drained()
        return handles

    srv = InferenceServer(scfg)
    offered, results = 0, []
    wave_pre = _wave(srv)  # clean wave at halo@4
    monkeypatch.setenv(chaos.CHAOS_ENV, "seed=3,mesh_shrink=1")
    chaos.reset()
    wave_loss = _wave(srv)  # seeded loss: trip -> degrade -> replay
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()
    degraded = srv.sup.entry.key
    assert [t.kind for t in srv.sup.trips] == ["mesh_shrink"]
    assert srv.sup.pool.n_alive == 7
    srv.sup.pool.heal(srv.sup.pool.recently_lost(1), cause="drill:heal")
    assert srv.sup.pool.n_probation == 1
    # One wave = two clean batches = the full probation (N=2). Promotion
    # must NOT fire inside it — the device graduates on its last batch.
    wave_prob = _wave(srv)
    assert srv.sup.promotions == 0  # hysteresis: nothing during probation
    assert srv.sup.pool.n_probation == 0  # ...but the device graduated
    assert srv.sup.entry.key == degraded
    wave_post = _wave(srv)  # first step promotes, then dispatches at halo@4
    assert srv.sup.promotions == 1 and srv.stats.promotions == 1
    assert srv.sup.entry.key == "halo@4:reference"
    assert srv.sup.pool.summary() == "8/8"
    # accounting + the zero-miss discipline across the WHOLE lifecycle
    all_handles = [wave_pre, wave_loss, wave_prob, wave_post]
    assert all(h.status == OK for wave in all_handles for h in wave)
    assert srv.stats.cache_misses == 0
    kinds = [r["kind"] for r in Journal.load(jpath)]
    for a, b in [("mesh_shrink", "mesh_probation"),
                 ("mesh_probation", "sup_promote")]:
        assert kinds.index(a) < kinds.index(b)
    # the promotion's re-warm lands BEFORE the first post-promotion batch
    assert (
        len([k for k in kinds if k == "serve_rewarm"]) == 2
    )  # one per degrade, one per promote
    # every wave bit-identical to a clean server pinned to its topology
    for wave, entry_key in [(wave_pre, "halo@4:reference"),
                            (wave_loss, degraded),
                            (wave_post, "halo@4:reference")]:
        from cuda_mpi_gpu_cluster_programming_tpu.resilience.supervisor import (
            LadderEntry,
        )

        strategy, rest = entry_key.split("@")
        n, tier = rest.split(":")
        clean = InferenceServer(
            dataclasses.replace(scfg, journal_path=""),
            ladder=[LadderEntry(strategy, tier, int(n))],
        )
        clean_handles = [clean.submit(im) for im in imgs]
        clean.run_until_drained()
        for a, b in zip(wave, clean_handles):
            assert b.status == OK
            assert np.array_equal(a.result, b.result)


def test_threaded_poisson_load_accounts_for_every_request(tmp_path):
    jpath = tmp_path / "serve.jsonl"
    srv = InferenceServer(
        ServeConfig(config="v1_jit", max_batch=4, model_cfg=CFG,
                    journal_path=str(jpath))
    ).start()
    try:
        report = run_load(srv, rate_rps=60.0, duration_s=0.4, seed=1)
    finally:
        srv.stop()
    assert report.n_requests > 0
    assert (
        report.n_ok + report.n_shed + report.n_failed + report.n_rejected
        == report.n_requests
    )
    assert report.n_ok == report.n_requests  # unloaded CPU: nothing sheds
    assert report.p50_ms is not None and report.p99_ms >= report.p50_ms
    assert report.sustained_img_s > 0
    assert srv.stats.cache_misses == 0
    # the journaled latencies are the same population the report saw
    assert len(request_latencies_from_journal(jpath)) == report.n_ok


# ----------------------------------------------------------- CLI surfaces ---


def test_run_cli_serve_smoke(tmp_path):
    jpath = tmp_path / "serve.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
         "--config", "v1_jit", "--serve", "--serve-rate", "30",
         "--serve-duration", "0.4", "--serve-max-batch", "4",
         "--height", "63", "--width", "63",
         "--serve-journal", str(jpath)],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.splitlines()
    load = next(l for l in lines if l.startswith("Serve load: "))
    serve = next(l for l in lines if l.startswith("Serve: "))
    assert "p50_ms=" in load and "img_s=" in load
    assert "cache_misses=0" in serve and "buckets=1,2,4" in serve
    assert request_latencies_from_journal(jpath)


def test_run_cli_serve_rejects_full_model():
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_mpi_gpu_cluster_programming_tpu.run",
         "--config", "v6_full_jit", "--serve"],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "Blocks 1-2 configs only" in proc.stderr


def test_bench_serve_mode_cpu_smoke(tmp_path):
    """The tier-1 CPU-mesh serve smoke (ISSUE 6 CI satellite): a journaled
    Poisson run reporting p50/p99 + sustained img/s with ZERO post-warmup
    compile-cache misses, plus the in-load device_loss drill finishing all
    in-flight requests via supervisor replay, bit-identically."""
    jpath = tmp_path / "serve_bench.jsonl"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_MODE": "serve",
        "BENCH_SERVE_HEIGHT": "63",
        "BENCH_SERVE_WIDTH": "63",
        "BENCH_SERVE_DURATION": "0.5",
        "BENCH_SERVE_RATE": "40",
        "BENCH_SERVE_MAX_BATCH": "4",
        "BENCH_SERVE_JOURNAL": str(jpath),
    }
    proc = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        cwd=ROOT, timeout=540, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    row = json.loads(line)
    assert row["metric"] == "alexnet_blocks12_serve_images_per_sec"
    assert "error" not in row
    assert row["value"] > 0
    assert row["p50_ms"] > 0 and row["p99_ms"] >= row["p50_ms"]
    assert row["cache_misses_post_warmup"] == 0
    assert row["n_ok"] == row["n_requests"]
    assert row["buckets"] == [1, 2, 4]
    drill = row["drill"]
    assert drill["completed"] == drill["n_requests"]
    assert drill["trips"] == ["device_loss"]
    assert drill["replayed_in_flight"] is True
    assert drill["bit_identical"] is True
    # ISSUE 8: the drill sub-object's mesh_shrink row — the elastic path's
    # machine-comparable trajectory across BENCH_r* rounds.
    shrink = drill["mesh_shrink"]
    assert shrink["completed"] == shrink["n_requests"]
    assert shrink["trips"] == ["mesh_shrink"]
    assert shrink["devices_after"] < shrink["devices_before"]
    assert shrink["replayed"] == 1
    assert shrink["rewarm_ms"] > 0
    assert shrink["cache_misses_post_rewarm"] == 0
    # ISSUE 10: the drill sub-object's mesh_grow row — lose, heal,
    # probation, PROMOTE, with the throughput-recovery verdict.
    grow = drill["mesh_grow"]
    assert grow["completed"] == grow["n_requests"]
    assert grow["promotions"] == 1
    assert grow["trips"] == ["mesh_shrink"]
    assert grow["promoted_entry"] != grow["degraded_entry"]
    assert grow["recovered"] is True
    assert grow["recovery_ms"] > 0
    assert grow["pre_img_s"] > 0 and grow["post_img_s"] > 0
    assert grow["cache_misses_post_promote"] == 0
    assert grow["cache_misses_total"] == 0
    # the journal backs the reported percentiles
    assert len(request_latencies_from_journal(jpath)) == row["n_ok"]
    # ISSUE 9 CI satellite: serve rows carry a NON-EMPTY per-stage
    # breakdown (sentinel tap boundaries) alongside the zero-cache-miss
    # assertion above, the process metrics summary, and the trace id the
    # journal's spans correlate on.
    bd = row["breakdown"]
    assert set(bd["stages"]) == {"conv1", "pool1", "conv2", "pool2", "lrn2"}
    assert bd["stage_sum_ms"] > 0
    # ISSUE 13: serve rows carry the roofline join beside the breakdown,
    # at the geometry the service actually dispatches.
    rf = row["roofline"]
    assert rf["source"] == "breakdown"
    assert {s["name"] for s in rf["stages"]} == set(bd["stages"])
    assert all(s["bound"] in ("compute", "memory") for s in rf["stages"])
    assert set(rf["blocks"]) == {"block1", "block2"}
    metrics = row["metrics"]
    assert metrics["serve.ok"] == row["n_ok"]
    assert metrics["serve.batch_ms"]["count"] >= 1
    assert metrics["serve.batch_ms"]["p50"] > 0
    assert row["trace_id"]
    # the serve journal doubles as the span trail: dispatch + queue-wait
    # spans landed beside their serve_batch records, exportable as one
    # Perfetto timeline
    from cuda_mpi_gpu_cluster_programming_tpu.resilience.journal import Journal

    recs = Journal.load(jpath)
    span_names = {r["name"] for r in recs if r["kind"] == "span"}
    assert {"serve.dispatch", "serve.queue_wait", "serve.warmup"} <= span_names
    batches = [r for r in recs if r["kind"] == "serve_batch"]
    assert batches and all(r.get("trace_id") == row["trace_id"] for r in batches)
    kinds = {r["kind"] for r in recs}
    assert {"serve_gauges", "mem_snapshot"} <= kinds

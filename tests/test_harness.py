"""Harness tests: triage classification, stdout-contract parsing, CSV schema,
ASCII table, and one real subprocess sweep on the virtual CPU mesh.

Reference analogue: the bash pipeline of scripts/common_test_utils.sh
(classify :96-116, CSV :71-81, table :133-178) and the sweep drivers.
"""

import csv

from cuda_mpi_gpu_cluster_programming_tpu import harness


def test_classify_ok():
    assert harness.classify(0, "anything") == harness.OK


def test_classify_env_warn():
    assert harness.classify(1, "RuntimeError: Unable to initialize backend 'tpu'") == harness.ENV_WARN


def test_classify_mesh_warn():
    text = "ValueError: config 'v2.2_sharded' with 4 shards needs 4 devices, have 1"
    assert harness.classify(2, text) == harness.MESH_WARN


def test_classify_critical():
    assert harness.classify(139, "Segmentation fault (core dumped)") == harness.CRITICAL


def test_classify_generic_fail():
    assert harness.classify(1, "ValueError: something else") == harness.FAIL


def test_classify_startup_chatter_does_not_mask_failure():
    # JAX's benign startup line must not reclassify a later real error.
    text = (
        "INFO: Unable to initialize backend 'tpu': not found\n"
        "Traceback (most recent call last):\n"
        + "  ...\n" * 10
        + "ValueError: actual bug in the run\n"
    )
    assert harness.classify(1, text) == harness.FAIL


def test_classify_axon_backend_error_is_env_warn():
    # Observed round 1 (BENCH_r01.json): axon registers but init fails.
    text = (
        "Traceback (most recent call last):\n"
        "  ...\n"
        "RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE: "
        "TPU backend setup/compile error (Unavailable).\n"
        "--------------------\n"
        "For simplicity, JAX has removed its internal frames from the "
        "traceback of the following exception.\n"
    )
    assert harness.classify(1, text) == harness.ENV_WARN


def test_classify_wedged_tunnel_timeout_triage():
    # Observed round 1 (MULTICHIP_r01.json, rc=124): the axon banner prints,
    # then execution blocks forever until the timeout wrapper kills the run.
    banner = (
        "WARNING:jax._src.xla_bridge:905: Platform 'axon' is experimental "
        "and not all JAX functionality may be correctly supported!\n"
    )
    # The banner ALONE is no longer a wedge signal (every run prints it —
    # a pre-compile framework deadlock would be masked). Without a probe
    # verdict the hang stays TIMEOUT.
    assert harness.classify(124, banner) == harness.TIMEOUT
    assert harness.classify_timeout(banner) == harness.TIMEOUT
    # The probe's explicit diagnosis in the log is decisive.
    assert (
        harness.classify_timeout(banner + "probe timed out after 45s (wedged tunnel?)")
        == harness.ENV_WARN
    )
    # An active probe verdict is decisive either way.
    assert harness.classify_timeout(banner, lambda: False) == harness.ENV_WARN
    assert harness.classify_timeout(banner, lambda: True) == harness.TIMEOUT


def test_classify_timeout_with_progress_is_real_timeout():
    # A run that got past compilation before the deadline genuinely timed
    # out — the axon banner alone must not excuse it.
    text = (
        "Platform 'axon' is experimental\n"
        "Compile time: 2000.0 ms\n"
    )
    assert harness.classify_timeout(text) == harness.TIMEOUT
    # progress beats even a dead-device probe verdict: the run was alive
    assert harness.classify_timeout(text, lambda: False) == harness.TIMEOUT
    # and a bare kill with no wedge signature stays TIMEOUT too
    assert harness.classify(124, "some unrelated output") == harness.TIMEOUT


def test_parse_run_log_full():
    r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
    r.run_status = harness.OK
    text = (
        "Compile time: 812.0 ms\n"
        "Final Output Shape: 13x13x256\n"
        "Final Output (first 10 values): 29.2932 25.9153 23.3255 1.0 2.0 3.0 4.0 5.0 6.0 7.0\n"
        "AlexNet TPU Forward Pass completed in 1.234 ms (amortized over 10 fenced passes; 810.4 img/s)\n"
    )
    harness.parse_run_log(text, r)
    assert r.parse_status == "OK"
    assert r.time_ms == 1.234
    assert r.compile_ms == 812.0
    assert r.shape == "13x13x256"
    assert r.first5.split() == ["29.2932", "25.9153", "23.3255", "1.0", "2.0"]
    assert r.status == harness.OK


def test_plan_hash_parsed_into_csv_row(tmp_path):
    """The run CLI's 'Tune plan:' line lands in the PlanHash CSV column, so
    tuned rows are attributable to one exact plan (docs/TUNING.md)."""
    for verb in ("swept", "cache", "loaded"):
        m = harness._RE_PLAN.search(
            f"Devices: 1 x cpu (cpu)\nTune plan: {verb} hash=0efe8300ae "
            "key=cpu|blocks12_227x227x3|b1|fp32|rev=abc path=perf/tune_plan.json\n"
        )
        assert m and m.group(1) == "0efe8300ae", verb
    session = harness.Session(log_root=tmp_path)
    r = harness.CaseResult("V3 CUDA", "v3_pallas", 1, 1)
    r.run_status = harness.OK
    r.plan_hash = "0efe8300ae"
    session.log_row(r)
    with open(session.csv_path) as f:
        rows = list(csv.reader(f))
    assert rows[1][rows[0].index("PlanHash")] == "0efe8300ae"


def test_parse_run_log_missing_fields_degrade_to_parse_err():
    # Missing fields → ⚠ Parse Error, not failure (common_test_utils.sh:319-324).
    r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
    r.run_status = harness.OK
    harness.parse_run_log("no contract lines here", r)
    assert r.parse_status == harness.PARSE_ERR
    assert r.status == harness.PARSE_ERR
    assert "time" in r.parse_msg and "shape" in r.parse_msg


def test_summary_table_renders():
    r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
    r.run_status = harness.OK
    r.time_ms = 1.5
    r.shape = "13x13x256"
    r.first5 = "29.2932 25.9153"
    table = harness.summary_table([r])
    assert "┌" in table and "└" in table
    assert "V1 Serial" in table and "13x13x256" in table


def test_session_csv_schema(tmp_path):
    session = harness.Session(log_root=tmp_path)
    r = harness.CaseResult("V1 Serial", "v1_jit", 1, 1)
    r.run_status = harness.OK
    r.time_ms = 2.0
    session.log_row(r)
    with open(session.csv_path) as f:
        rows = list(csv.reader(f))
    assert rows[0] == harness.CSV_COLUMNS
    # The reference's 20-column schema + the 2 resilience attempt-metadata
    # columns + the tuning PlanHash column + the supervisor incident column
    # + the precision Dtype column (each appended, so historical column
    # indexes are untouched).
    assert len(rows[0]) == 25
    assert rows[0][20:] == [
        "Attempts", "ResilienceMsg", "PlanHash", "SupervisorMsg", "Dtype",
    ]
    assert rows[1][4] == "V1 Serial"
    assert rows[1][14] == harness.OK
    assert rows[1][20] == "1"  # single attempt, no retries
    assert rows[1][22] == ""  # untuned row: no plan hash
    assert rows[1][23] == ""  # unsupervised row: no incident trail
    assert rows[1][24] == ""  # no Precision line parsed: pre-policy log


def test_run_case_subprocess_sweep(tmp_path):
    """End-to-end: real subprocess runs of v1_jit and v2.2_sharded (np=2) on
    a tiny image over the virtual CPU mesh — the --oversubscribe analogue."""
    session = harness.Session(log_root=tmp_path)
    extra = ["--height", "63", "--width", "63", "--repeats", "2", "--warmup", "1"]
    r1 = harness.run_case(
        session, "v1_jit", "V1 Serial", 1, 1, timeout_s=240, fake_devices=2, extra_args=extra
    )
    assert r1.status == harness.OK, (r1.run_msg, r1.parse_msg)
    assert r1.shape == "2x2x256"  # 63 -> conv1 14 -> pool1 6 -> conv2 6 -> pool2 2
    r2 = harness.run_case(
        session, "v2.2_sharded", "V2.2 ScatterHalo", 2, 1, timeout_s=240, fake_devices=2, extra_args=extra
    )
    assert r2.status == harness.OK, (r2.run_msg, r2.parse_msg)
    assert r2.shape == "2x2x256"
    # Sharded and single-device runs agree on the contract values (the
    # reference's cross-version first-5 oracle, SURVEY §4.3).
    assert r1.first5 == r2.first5
    # Mesh-starved case triages as a warning, not a failure.
    r3 = harness.run_case(
        session, "v2.2_sharded", "V2.2 ScatterHalo", 4, 1, timeout_s=240, fake_devices=2, extra_args=extra
    )
    assert r3.status == harness.MESH_WARN
    with open(session.csv_path) as f:
        assert len(list(csv.reader(f))) == 4  # header + 3 rows


def test_classify_remote_compile_5xx_is_env_warn():
    """The tunnel's remote-compile relay fails transiently with HTTP 5xx
    (observed round 3; same configs compiled clean minutes later) — an
    environment fault, not a framework failure."""
    log = (
        "Devices: 1 x TPU v5 lite (tpu)\n"
        "JaxRuntimeError: INTERNAL: http://127.0.0.1:8103/remote_compile: "
        "HTTP 500: tpu_compile_helper subprocess exit code 1\n"
    )
    assert harness.classify(1, log) == harness.ENV_WARN
    # a plain framework ValueError after the banner still FAILs
    assert harness.classify(1, "Devices: 1 x TPU v5 lite (tpu)\nValueError: boom\n") == harness.FAIL
